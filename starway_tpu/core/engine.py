"""Worker engines: the progress/completion runtime behind Client and Server.

The reference dedicates one 100%-CPU busy-poll thread per Client/Server
(``start_working``, reference: src/bindings/main.cpp:361-468, 1126-1268) and
hands ops over capacity-1 spin channels (src/bindings/chan.hpp:84-119).  On a
TPU host the CPU belongs to XLA dispatch, so this build replaces that design
with one *event-driven* engine thread per worker: a ``selectors`` loop woken
by a socketpair when the application submits an op -- zero CPU when idle, same
ownership model (the engine thread is the only thread that touches sockets).

Submission is an unbounded FIFO deque rather than a capacity-1 rendezvous
slot; ordering guarantees are identical (ops of one worker execute in
submission order) and the application never blocks on submission.

Completion flows the same way as the reference: transport event -> engine
thread -> user callback (which typically trampolines into asyncio via
``loop.call_soon_threadsafe``; reference: src/starway/__init__.py:124-128).
All user callbacks are invoked outside the worker lock.
"""

from __future__ import annotations

import heapq
import itertools
import json
import logging
import random
import selectors
import socket
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Callable, Optional

from .. import config, perf
from ..errors import (
    REASON_CANCELLED,
    REASON_NOT_CONNECTED,
    REASON_SESSION_EXPIRED,
    REASON_TIMEOUT,
    StarwayStateError,
)
from . import fabric, frames, state, swtrace, telemetry
from .conn import InprocConn, TcpConn
from .lane import StripeSource
from .session import SessionState
from .endpoint import ServerEndpoint
from .matching import PostedRecv, TagMatcher

logger = logging.getLogger("starway_tpu")


def _run_fires(fires) -> None:
    for f in fires:
        if f is None:
            continue
        try:
            f()
        except Exception:
            logger.exception("starway: user callback raised")


class FlushRec:
    """One outstanding flush barrier (worker- or endpoint-scoped).

    Completes when every targeted connection has acknowledged the flush
    sequence issued to it -- the analogue of ``ucp_worker_flush_nbx`` /
    ``ucp_ep_flush_nbx`` completion (reference: src/bindings/main.cpp:432,1202).
    """

    __slots__ = ("done", "fail", "waits", "stripe_waits", "completed", "born")

    def __init__(self, done, fail):
        self.done = done
        self.fail = fail
        # swpulse (§25): barrier birth stamp for the flush_us distribution
        # and the stall sentinel's outlived-threshold check.
        self.born = time.perf_counter()
        self.waits: dict = {}  # conn -> seq
        # Striped delivery rides SACKs, not per-rail FLUSH frames (rails
        # carry only chunk traffic): the barrier additionally waits until
        # every striped source submitted before it (msg_id <= watermark)
        # is SACKed (DESIGN.md §17).
        self.stripe_waits: dict = {}  # primary conn -> msg_id watermark
        self.completed = False


class Worker:
    kind = "worker"
    # The TX pump understands chunked payload duck types (TxData in
    # core/conn.py): device.py routes incremental device-to-host staging
    # through this engine only.  The native engine stages via a flat host
    # view instead (its ABI takes a raw pointer + length).
    supports_chunked_tx = True

    def __init__(self, name: str = ""):
        self.lock = threading.RLock()
        self.status = state.VOID
        self.worker_id = uuid.uuid4().hex
        self.name = name or self.worker_id[:8]
        self.matcher = TagMatcher()
        # swtrace observability (DESIGN.md §13): the counter registry is
        # always live (plain int increments); the trace ring and the
        # per-op callback wraps exist only when STARWAY_TRACE /
        # STARWAY_FLIGHT_DIR armed them -- the off path is one `is None`
        # check per op.
        self.counters = swtrace.Counters()
        # swpulse distributions (DESIGN.md §25): always live, like the
        # counters -- one clock read + one array increment per bump.
        self.hists = swtrace.Hists()
        # swpulse stall sentinel (§25): condition keys already alerted on,
        # so a wedge raises ONE alert until it clears (telemetry thread
        # calls stall_scan; empty and untouched unless STARWAY_STALL_MS).
        self._stall_seen: set = set()
        self._trace = swtrace.worker_ring()
        self._faulted = False
        self.matcher.counters = self.counters
        self.matcher.hists = self.hists
        self.matcher.trace = self._trace
        # §18 flow control: the matcher's grant hook runs under the
        # worker lock and only enqueues an engine op (conn TX is
        # engine-thread territory).
        self.matcher.fc_grant = self._fc_enqueue_grant
        self.stage_scope = perf.StageScope(ring=self._trace)
        swtrace.register_worker(self)
        telemetry.register_worker(self)
        self.ops: deque = deque()
        # Ops queued or currently executing on the engine thread.  When zero,
        # in-process sends/flushes may run inline on the caller thread (no
        # thread hop) without breaking FIFO ordering: submissions are
        # serialized by the caller, and nothing is concurrently draining.
        self._busy = 0
        self.conns: dict = {}  # conn_id -> conn
        self.flush_records: list[FlushRec] = []
        self.close_cb: Optional[Callable[[], None]] = None
        self.selector: Optional[selectors.BaseSelector] = None
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.thread: Optional[threading.Thread] = None
        self._listener: Optional[socket.socket] = None
        # Deadline timers: heap of (monotonic deadline, seq, fn(fires)).
        # Armed from app threads under the lock; fired on the engine thread
        # (the selector timeout tracks the earliest entry).  Settled ops
        # leave their timer behind as a harmless no-op.
        self._timers: list = []
        self._timer_seq = itertools.count()
        # Peer-liveness keepalive (config.keepalive_interval); sampled at
        # engine start so one worker's lifetime sees one config.
        self._ka_interval = 0.0
        self._ka_misses = 3
        self.mode = "socket"
        self._address_blob: Optional[bytes] = None
        # PJRT transfer manager for cross-process device payloads
        # (device.py TransferManager); created lazily, dropped at close so
        # unpulled sends die with the worker (close-cancel contract).
        self._xfer_mgr = None

    # ------------------------------------------------------------ app side
    def _require_running(self) -> None:
        if self.status != state.RUNNING:
            raise StarwayStateError(
                f"starway {self.kind} is not in a running state "
                f"(status={state.NAMES[self.status]})"
            )

    # --------------------------------------------------------- observability
    @property
    def trace_label(self) -> str:
        return f"{self.kind}-{self.name}"

    def trace_events(self) -> list:
        """Snapshot of this worker's swtrace ring ([] when tracing off)."""
        return self._trace.snapshot() if self._trace is not None else []

    def counters_snapshot(self) -> dict:
        """This worker's counter registry, with the process-global
        counters (staging pool, reconnects) overlaid -- the same shape the
        native engine surfaces through ``sw_counters``."""
        return swtrace.merge_global_counters(self.counters.snapshot())

    def hists_snapshot(self) -> dict:
        """The §25 swpulse distributions: ``{name: [HIST_BUCKETS counts]}``
        in the shared HIST_NAMES vocabulary -- the same shape the native
        engine surfaces through ``sw_hists``.  Percentiles are derived at
        read time (swtrace.hist_summary)."""
        return self.hists.snapshot()

    def stall_scan(self, threshold_s: float, progressed: bool = False) -> list:
        """swpulse stall sentinel (DESIGN.md §25): flag no-progress
        conditions older than ``threshold_s``.  Called from the telemetry
        thread when STARWAY_STALL_MS armed it (never on the seed path);
        ``progressed`` means the worker's counters moved since the last
        scan, which clears every suspicion -- the sentinel flags *wedges*,
        not slowness.  Each NEW condition bumps ``stall_alerts`` and lands
        an EV_STALL event in the trace ring; a condition alerts once until
        it clears.  Returns structured report dicts."""
        now = time.perf_counter()
        reports: list = []
        with self.lock:
            live: set = set()
            if not progressed and self.status == state.RUNNING:
                for rec in self.flush_records:
                    age = now - rec.born
                    if age <= threshold_s:
                        continue
                    key = (swtrace.STALL_REASONS[0], id(rec))
                    live.add(key)
                    if key not in self._stall_seen:
                        reports.append({
                            "reason": swtrace.STALL_REASONS[0], "conn": 0,
                            "age_ms": int(age * 1e3),
                            "detail": f"flush barrier pending "
                                      f"{len(self.flush_records)} record(s)",
                        })
                for c in self.conns.values():
                    sess = getattr(c, "sess", None)
                    if sess is not None and sess.suspended:
                        continue  # §14 resume owns progress; not a wedge
                    fw = getattr(c, "fc_waiting", None)
                    if fw:
                        t0 = getattr(fw[0], "t_park", 0.0)
                        age = now - t0 if t0 else 0.0
                        if age > threshold_s:
                            key = (swtrace.STALL_REASONS[1], c.conn_id)
                            live.add(key)
                            if key not in self._stall_seen:
                                reports.append({
                                    "reason": swtrace.STALL_REASONS[1],
                                    "conn": c.conn_id,
                                    "age_ms": int(age * 1e3),
                                    "detail": f"{len(fw)} parked send(s), "
                                              f"no credit arrival",
                                })
                    grp = getattr(c, "stripe", None)
                    if grp is not None:
                        pinned = [s for s in grp.by_id.values()
                                  if not s.sacked and not s.failed
                                  and now - s.t_post > threshold_s]
                        if pinned:
                            key = (swtrace.STALL_REASONS[2], c.conn_id)
                            live.add(key)
                            if key not in self._stall_seen:
                                age = now - min(s.t_post for s in pinned)
                                reports.append({
                                    "reason": swtrace.STALL_REASONS[2],
                                    "conn": c.conn_id,
                                    "age_ms": int(age * 1e3),
                                    "detail": f"{len(pinned)} un-SACKed "
                                              f"stripe pin(s)",
                                })
                un = self.matcher.unexpected
                if un and now - un[0].born > threshold_s:
                    key = (swtrace.STALL_REASONS[3], 0)
                    live.add(key)
                    if key not in self._stall_seen:
                        reports.append({
                            "reason": swtrace.STALL_REASONS[3], "conn": 0,
                            "age_ms": int((now - un[0].born) * 1e3),
                            "detail": f"{len(un)} unexpected message(s) "
                                      f"unclaimed",
                        })
            self._stall_seen = live
            if reports:
                self.counters.stall_alerts += len(reports)
                tr = self._trace
                if tr is not None:
                    for r in reports:
                        tr.rec(swtrace.EV_STALL, 0, r["conn"], r["age_ms"],
                               r["reason"])
        for r in reports:
            r["worker"] = self.trace_label
        return reports

    def gauges_snapshot(self) -> dict:
        """Instantaneous per-conn gauges (telemetry.GAUGE_NAMES) plus the
        worker-level ``posted_recvs`` and the process-global staging-pool
        occupancy -- the shape the native engine surfaces through the
        ``sw_gauges`` ABI call (DESIGN.md §15).  Only the conn list and
        the posted count are read under the worker lock; the per-conn
        values are then read lock-free (telemetry.conn_gauges tolerates
        torn reads -- a skewed sample, never a crash).  Every gauge
        drains to 0 on an idle, flushed worker."""
        with self.lock:
            conns = list(self.conns.values())
            posted = len(self.matcher.posted)
        snap = {
            "conns": {c.conn_id: telemetry.conn_gauges(c) for c in conns},
            "posted_recvs": posted,
            # §24: native-only lever; this engine has no submission ring.
            "uring_depth": 0,
        }
        return telemetry.merge_global_gauges(snap)

    def post_recv(self, buf, tag: int, mask: int, done, fail, owner=None,
                  timeout: Optional[float] = None) -> None:
        tr = self._trace
        if tr is not None:
            nbytes = int(buf.nbytes if hasattr(buf, "nbytes") else len(buf))
            done, fail = swtrace.wrap_op(self, tr, swtrace.EV_RECV_DONE,
                                         tag, 0, nbytes, done, fail)
        pr = PostedRecv(buf, tag, mask, done, fail, owner)
        with self.lock:
            self._require_running()
            # Counted/recorded only once the submit is accepted (the C++
            # engine bumps after its status check too -- one accounting);
            # RECV_POST lands before the matcher can record RECV_MATCH.
            self.counters.recvs_posted += 1
            if tr is not None:
                tr.rec(swtrace.EV_RECV_POST, tag, 0, nbytes)
            fires = self.matcher.post_recv_pr(pr)
        if timeout is not None:
            # The timer holds the receive WEAKLY: the matcher is the only
            # strong owner while it pends, so a settled receive (and its
            # buffer) is collectable immediately and the late timer no-ops.
            ref = weakref.ref(pr)
            self._add_timer(timeout, lambda fires, r=ref: self._expire_recv_ref(r, fires))
        _run_fires(fires)

    def submit_send(self, conn, view, tag: int, done, fail, owner=None,
                    timeout: Optional[float] = None) -> None:
        nbytes = int(view.nbytes if hasattr(view, "nbytes") else len(view))
        tr = self._trace
        if tr is not None:
            cid = conn.conn_id if conn is not None else 0
            done, fail = swtrace.wrap_op(self, tr, swtrace.EV_SEND_DONE,
                                         tag, cid, nbytes, done, fail)
        inline = False
        with self.lock:
            self._require_running()
            self.counters.sends_posted += 1  # accepted-submit accounting
            self.hists.msg_bytes[swtrace.hist_bucket(nbytes)] += 1  # §25
            if tr is not None:
                tr.rec(swtrace.EV_SEND_POST, tag, cid, nbytes)
            if self._busy == 0 and conn is not None and conn.kind == "inproc" and conn.alive:
                inline = True
            else:
                self._busy += 1
                self.ops.append(("send", conn, view, tag, done, fail, owner, timeout))
        if inline:
            # Synchronous delivery: the op settles before a deadline could
            # ever be armed, so `timeout` is moot here.
            fires: list = []
            conn.send_data(tag, view, done, fail, owner, fires)
            _run_fires(fires)
            return
        self._wake()

    def submit_flush(self, done, fail, conns=None,
                     timeout: Optional[float] = None) -> None:
        tr = self._trace
        if tr is not None:
            done, fail = swtrace.wrap_op(self, tr, swtrace.EV_FLUSH_DONE,
                                         0, 0, 0, done, fail)
        inline = False
        with self.lock:
            self._require_running()
            self.counters.flushes_posted += 1  # accepted-submit accounting
            if tr is not None:
                tr.rec(swtrace.EV_FLUSH_POST)
            targets = conns if conns is not None else list(self.conns.values())
            # Inline only when the engine owns no TCP state at all: flush
            # bookkeeping (flush_records) is engine-thread territory
            # otherwise (TCP acks mutate it concurrently).
            if self._busy == 0 and all(c.kind == "inproc" for c in self.conns.values()):
                inline = True
            else:
                self._busy += 1
                self.ops.append(("flush", done, fail, conns, timeout))
        if inline:
            # All in-process traffic already delivered synchronously in
            # submission order: the barrier is trivially met.
            fires = []
            self._start_flush(done, fail, targets, fires, timeout)
            _run_fires(fires)
            return
        self._wake()

    def submit_devpull(self, conn, desc: dict, tag: int, done, fail, owner) -> None:
        """Queue a DEVPULL descriptor send (device.py decided the payload
        rides the pull path).  Always via the engine thread: descriptor
        ordering in the stream is what the flush barrier builds on."""
        from . import frames as _frames

        nbytes = int(desc.get("n", 0))
        tr = self._trace
        if tr is not None:
            cid = conn.conn_id if conn is not None else 0
            done, fail = swtrace.wrap_op(self, tr, swtrace.EV_SEND_DONE,
                                         tag, cid, nbytes, done, fail)
        data = _frames.pack_devpull(tag, desc)
        with self.lock:
            self._require_running()
            self.counters.sends_posted += 1  # accepted-submit accounting
            self.hists.msg_bytes[swtrace.hist_bucket(nbytes)] += 1  # §25
            if tr is not None:
                tr.rec(swtrace.EV_SEND_POST, tag, cid, nbytes)
            self._busy += 1
            self.ops.append(("devpull", conn, data, done, fail, owner))
        self._wake()

    def transfer_manager(self):
        """The worker's TransferManager, created on first use (None when
        the PJRT transfer API is unavailable)."""
        from .. import device as _device

        with self.lock:
            if self._xfer_mgr is None:
                if not _device.devpull_supported():
                    return None
                self._xfer_mgr = _device.TransferManager(config.advertised_host())
            return self._xfer_mgr

    # -------------------------------------------------------- flow control
    def _fc_enqueue_grant(self, conn, gen: int, nbytes: int) -> None:
        """Matcher fc_release hook: hop the window grant onto the engine
        thread.  Reentrant-safe (the worker lock is an RLock; the hook
        fires from matcher paths already holding it)."""
        with self.lock:
            if self.status != state.RUNNING:
                return
            self._busy += 1
            self.ops.append(("fc_grant", conn, gen, nbytes))
        self._wake()

    def _on_rts(self, conn, tag: int, info: dict, fires) -> None:
        """A §18 rendezvous announcement arrived (conn.fc_on_rts owns the
        mechanics).  Malformed fields parse as a drop, never a raise on
        the engine thread (the _sess_int discipline)."""
        if not conn.fc_ok:
            return  # never negotiated: drop (protocol-violating peer)
        msg_id = self._sess_int(info.get("m", 0))
        total = self._sess_int(info.get("n", 0))
        if msg_id == 0:
            return
        conn.fc_on_rts(tag, msg_id, total, fires)

    # ------------------------------------------------------ devpull inbound
    def _on_devpull(self, conn, tag: int, desc: dict, fires) -> None:
        from .. import device as _device

        mgr = self.transfer_manager()
        if mgr is None:
            # We never advertised the capability; a peer sending DEVPULL
            # anyway gets the message dropped (descriptor unpullable here).
            return
        # Peer-supplied size: the _sess_int discipline (missing/garbled
        # parses as 0, like the C++ engine's json_num_field) -- a
        # malformed descriptor must not raise on the engine thread.
        nbytes = self._sess_int(desc.get("n", 0))
        remote = _device.RemoteMsg(desc, conn, mgr)
        with self.lock:
            msg, f = self.matcher.on_remote_message(tag, nbytes, remote)
        fires.extend(f)
        conn.remote_received(msg)
        if msg.discard:
            # Truncation: the receive already failed, but the sender's
            # transfer server still holds the array.  Drain-pull it (result
            # dropped by on_remote_complete) so the sender's memory is
            # released; resolution also releases any flush barriers.
            fires.append(lambda m=msg: m.remote.start(m))

    def _on_pull_done(self, msg, payload, error) -> None:
        """Completion callback from the TransferManager thread.

        Conn I/O (deferred flush ACKs) is engine-thread territory, so hop
        onto the engine via the op queue; a worker already closing only
        needs the matcher bookkeeping."""
        with self.lock:
            if self.status == state.RUNNING:
                self._busy += 1
                self.ops.append(("pull_done", msg, payload, error))
                queued = True
            else:
                fires = self.matcher.on_remote_complete(msg, payload, error)
                queued = False
        if queued:
            self._wake()
        else:
            _run_fires(fires)

    def _force_start_pulls(self, conn, fires) -> None:
        """A FLUSH barrier arrived with descriptors still waiting for a
        matching receive: pull them now (into spill arrays) so the ACK can
        truthfully mean "payloads resident here".  The posted/started reads
        race against app-thread claims, but start() is idempotent under the
        worker lock, so a duplicate thunk is a cheap no-op."""
        with self.lock:
            pending = [m for m in conn._remote_msgs
                       if m.posted is None and not m.remote.started]
        for msg in pending:
            fires.append(lambda m=msg: m.remote.start(m))

    def close(self, cb) -> None:
        if self._faulted:
            # Post-mortem snapshot before teardown wipes the state the
            # fault left behind (DESIGN.md §13 flight recorder).
            swtrace.flight_dump("close-after-fault", self)
        with self.lock:
            self._require_running()
            self.status = state.CLOSING
            self.close_cb = cb
        self._wake()

    def force_close(self) -> None:
        """Destructor path: initiate close without a callback and without
        joining (engine threads are daemons).  Must never hang or raise --
        the reference pins this with del + gc.collect()
        (tests/test_basic.py:666-686)."""
        with self.lock:
            if self.status not in (state.INIT, state.RUNNING):
                return
            self.status = state.CLOSING
            self.close_cb = None
        try:
            self._wake()
        except OSError:
            pass

    def get_worker_address(self) -> bytes:
        if self._address_blob is None:
            self._address_blob = json.dumps(
                {
                    "worker_id": self.worker_id,
                    "host": config.advertised_host(),
                    "port": 0,
                    "fabric": "starway-tpu",
                }
            ).encode()
        return self._address_blob

    def _perf_transport(self, conn) -> str:
        with self.lock:
            self._require_running()
            if conn is None:
                return "tcp"
            if getattr(conn, "sm_negotiated", False):
                return "sm"
            return conn.kind

    def evaluate_perf(self, conn, msg_size: int) -> float:
        # Per-endpoint first (live-calibrated, perf.autocalibrate[_ep]),
        # transport-class model otherwise.
        return perf.conn_estimate(conn, self._perf_transport(conn), msg_size)

    def evaluate_perf_detail(self, conn, msg_size: int) -> dict:
        detail = perf.conn_estimate_detail(conn, self._perf_transport(conn),
                                           msg_size, scope=self.stage_scope)
        detail["counters"] = self.counters_snapshot()
        detail["telemetry"] = telemetry.detail_for(self)
        return detail

    # --------------------------------------------------------- engine side
    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # wake buffer full => engine already has a pending wake

    def _start_thread(self) -> None:
        self.thread = threading.Thread(
            target=self._run, name=f"starway-{self.kind}-{self.name}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        try:
            self.selector = selectors.DefaultSelector()
            self.selector.register(self._wake_r, selectors.EVENT_READ, self._on_wake)
            self._ka_interval = config.keepalive_interval()
            self._ka_misses = config.keepalive_misses()
            if not self._setup():
                self._teardown_sockets()
                return
            if self._ka_interval > 0:
                self._add_timer(self._ka_interval, self._ka_tick)
            while True:
                with self.lock:
                    if self.status == state.CLOSING:
                        break
                    timeout = None
                    if self._timers:
                        timeout = max(0.0, self._timers[0][0] - time.monotonic())
                try:
                    events = self.selector.select(timeout)
                except OSError:
                    break
                # One fires batch per wakeup: every completion this pass
                # produces (I/O events, due timers, drained ops) is
                # delivered in a single sweep after all engine work, so a
                # burst of N completions crosses into user code -- and
                # through the api layer's asyncio trampoline -- as one
                # batch, not N wakeups (mirrors the native engine's
                # per-epoll-pass FireList).
                fires: list = []
                try:
                    for key, mask in events:
                        key.data(mask, fires)
                    self._run_timers(fires)
                    self._drain_ops(fires)
                finally:
                    # Deliver even when a later handler in the sweep
                    # raises: completions already collected belong to ops
                    # the matcher/tx queues no longer track, so dropping
                    # them would hang their futures past emergency close.
                    _run_fires(fires)
            self._do_close()
        except Exception:
            logger.exception("starway: engine thread crashed; emergency close")
            swtrace.flight_dump("engine-crash", self)
            try:
                self._do_close()
            except Exception:
                pass

    def _setup(self) -> bool:
        raise NotImplementedError

    def _on_wake(self, mask, fires) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _drain_ops(self, fires: list) -> None:
        # Sends queue their tx items with the kick deferred, and every
        # touched conn is kicked ONCE after the whole backlog is queued:
        # a burst of small sends then leaves in single gathered sendmsg
        # passes instead of one syscall per op (core/conn.py _gather_tx).
        pending_kicks: set = set()
        try:
            while True:
                with self.lock:
                    if not self.ops or self.status != state.RUNNING:
                        return
                    op = self.ops.popleft()
                try:
                    self._process_op(op, fires, pending_kicks)
                finally:
                    with self.lock:
                        self._busy -= 1
        finally:
            for conn in pending_kicks:
                if conn.alive:
                    conn.kick_tx(fires)

    # ------------------------------------------------------------ deadlines
    def _add_timer(self, delay: float, fn) -> None:
        """Arm ``fn(fires)`` to run on the engine thread after ``delay``
        seconds.  Callable from any thread."""
        with self.lock:
            heapq.heappush(
                self._timers, (time.monotonic() + delay, next(self._timer_seq), fn)
            )
        self._wake()

    def _run_timers(self, fires: list) -> None:
        while True:
            with self.lock:
                if not self._timers or self._timers[0][0] > time.monotonic():
                    return
                if self.status != state.RUNNING:
                    return
                _, _, fn = heapq.heappop(self._timers)
            try:
                fn(fires)
            except Exception:
                logger.exception("starway: deadline timer raised")

    def _expire_recv_ref(self, ref, fires) -> None:
        pr = ref()
        if pr is None:
            return  # settled and collected: nothing to expire
        with self.lock:
            expired = self.matcher.expire_recv(pr)
        if expired:
            self.counters.ops_timed_out += 1
        fires.extend(expired)

    def _expire_send_ref(self, conn, ref, fires) -> None:
        item = ref()
        if item is None:
            return  # settled and collected
        self._expire_send(conn, item, fires)

    def _expire_send(self, conn, item, fires) -> None:
        """A deadline expired on a queued send.  An untouched item is
        withdrawn cleanly; one already partially on the wire cannot be
        unsent without corrupting the frame stream, so the conn is torn
        down (the UCX endpoint-error analogue)."""
        if isinstance(item, StripeSource):
            self._expire_stripe(conn, item, fires)
            return
        started = False
        shed = False
        with self.lock:
            if item.local_done:
                return  # settled (completed locally, or cancelled)
            # A sequenced session frame was already promised to the peer
            # (withdrawing it would leave a seq hole the receiver must
            # treat as a gap): expire it like a started send.  An
            # RTS-announced rendezvous send is promised the same way --
            # the receiver holds a record a silent withdrawal would wedge.
            started = (item.off > 0 or getattr(item, "sess_seq", 0) != 0
                       or (getattr(conn, "fc_ok", False)
                           and conn.fc_rts_state(item) is not None))
            sess = getattr(conn, "sess", None)
            if started and sess is not None and not sess.expired:
                # Live session, sequenced frame: the send is PROMISED.
                # The journal delivers it -- now, or via a replay after a
                # suspend -- so failing it "timed out" would lie about an
                # op the peer still receives (an app-level retry would
                # then duplicate the message), and tearing down a healthy
                # conn would force a needless resume cycle.  The op
                # completes late; only grace/epoch expiry may fail it
                # (DESIGN.md §14).  Deadlines can still fail a session
                # send while it is parked UNFRAMED by backpressure (no
                # seq assigned yet -- the clean-withdraw path below).
                return
            if not started:
                try:
                    conn.tx.remove(item)
                except ValueError:
                    # Session or flow-control backpressure may have
                    # parked it unframed.
                    sess = getattr(conn, "sess", None)
                    if sess is not None and item in sess.waiting:
                        sess.waiting.remove(item)
                    elif item in getattr(conn, "fc_waiting", ()):
                        # Deadline-aware load shedding (DESIGN.md §18):
                        # the receiver is saturated and this send's
                        # deadline arrived first -- fail it locally, the
                        # conn stays healthy.
                        conn.fc_waiting.remove(item)
                        shed = True
                    else:
                        return  # drained between checks
            item.local_done = True  # suppress the close-time cancel path
        self.counters.ops_timed_out += 1
        if shed:
            self.counters.sheds += 1
        if item.fail is not None:
            fires.append(lambda f=item.fail: f(REASON_TIMEOUT))
        if started:
            self._conn_broken(conn, fires)

    def _expire_stripe(self, conn, src, fires) -> None:
        """Deadline on a striped send (core/lane.py): an unstarted source
        withdraws cleanly; a started one has chunks promised on the wire,
        so the whole rail group resets -- unless a live session owns it
        (the per-message journal delivers it late, like any sequenced
        frame)."""
        with self.lock:
            if src.sacked or src.failed or src.local_done:
                return
            sess = getattr(conn, "sess", None)
            if src.started() and sess is not None and not sess.expired:
                return  # promised: re-dispatch at resume completes it late
        grp = getattr(conn, "stripe", None)
        if grp is None:
            return
        self.counters.ops_timed_out += 1
        if grp.expire(src, fires, REASON_TIMEOUT):
            self._conn_broken(conn, fires)

    def _expire_flush(self, rec, fires) -> None:
        if rec.completed:
            return
        rec.completed = True
        if rec in self.flush_records:
            self.flush_records.remove(rec)
        self.counters.ops_timed_out += 1
        if rec.fail is not None:
            fires.append(lambda f=rec.fail: f(REASON_TIMEOUT))

    # ------------------------------------------------------------ keepalive
    def _ka_tick(self, fires) -> None:
        """Recurring liveness sweep: PING quiet ka-negotiated conns, expire
        those silent past the miss window."""
        interval = self._ka_interval
        window = interval * self._ka_misses
        now = time.monotonic()
        with self.lock:
            conns = list(self.conns.values())
        expired = []
        for c in conns:
            if c.kind != "tcp" or not c.alive or not getattr(c, "ka_ok", False):
                continue
            if getattr(c, "sess", None) is not None and c.sess.suspended:
                continue  # no transport to probe; the grace timer governs
            if now - c.last_rx > window:
                expired.append(c)
            elif now - c.last_rx >= interval:
                c.send_ping(fires)
        for c in expired:
            self._conn_expired(c, fires)
        with self.lock:
            running = self.status == state.RUNNING
        if running:
            self._add_timer(interval, self._ka_tick)

    def _conn_expired(self, conn, fires) -> None:
        """Liveness window elapsed: declare the peer dead.  _conn_broken
        (liveness-active branch) fails the receive the conn was streaming
        into and, once no alive conns remain, every queued receive -- the
        keepalive-enabled replacement for recvs-pend-forever.  On a server
        with other live peers, queued (fan-in) receives stay postable."""
        logger.warning(
            "starway: peer %s liveness expired (%.3gs silent > %d x %.3gs)",
            conn.peer_name or conn.conn_id,
            time.monotonic() - conn.last_rx, self._ka_misses, self._ka_interval,
        )
        self.counters.ka_misses += 1
        self._conn_broken(conn, fires)

    def _process_op(self, op, fires, pending_kicks=None) -> None:
        if op[0] == "send":
            _, conn, view, tag, done, fail, owner, timeout = op
            if conn is None or not conn.alive:
                if fail is not None:
                    fires.append(lambda f=fail: f(REASON_NOT_CONNECTED))
                return
            defer = pending_kicks is not None and conn.kind != "inproc"
            item = conn.send_data(tag, view, done, fail, owner, fires,
                                  kick=not defer)
            if defer:
                pending_kicks.add(conn)
            if timeout is not None and item is not None and not item.local_done:
                # Weak, like the recv timer: the tx queue is the only
                # strong owner, so a drained send's payload is not pinned
                # for the rest of the timeout.
                ref = weakref.ref(item)
                self._add_timer(
                    timeout,
                    lambda fires, c=conn, r=ref: self._expire_send_ref(c, r, fires),
                )
        elif op[0] == "devpull":
            _, conn, data, done, fail, owner = op
            if conn is None or not conn.alive:
                if fail is not None:
                    fires.append(lambda f=fail: f(REASON_NOT_CONNECTED))
                return
            if pending_kicks is not None and conn.kind != "inproc":
                conn.send_devpull(data, done, fail, owner, fires, kick=False)
                pending_kicks.add(conn)
            else:
                conn.send_devpull(data, done, fail, owner, fires)
        elif op[0] == "pull_done":
            _, msg, payload, error = op
            with self.lock:
                fires.extend(self.matcher.on_remote_complete(msg, payload, error))
            msg.remote.conn.remote_resolved(msg, fires)
        elif op[0] == "fc_grant":
            _, conn, gen, nbytes = op
            if gen == conn.fc_rx_gen:
                conn.fc_unexp = max(0, conn.fc_unexp - nbytes)
                if conn.alive and conn.fc_ok and conn.sock is not None:
                    conn.send_ctl(frames.pack_credit(nbytes), fires)
        elif op[0] == "fc_cts":
            _, conn, msg = op
            conn.fc_start_rx(msg, fires)
        elif op[0] == "flush":
            _, done, fail, conns, timeout = op
            self._start_flush(done, fail, conns, fires, timeout)

    # -------------------------------------------------------------- flush
    def _start_flush(self, done, fail, conns, fires,
                     timeout: Optional[float] = None) -> None:
        with self.lock:
            candidates = conns if conns is not None else list(self.conns.values())
        # Secondary rails are never flush targets: they carry only chunk
        # traffic, and striped delivery is covered by the SACK waits below.
        candidates = [c for c in candidates
                      if getattr(c, "rail_parent", None) is None]
        # A dead connection with unacknowledged tagged data means the barrier
        # cannot truthfully complete: fail like a send on a dead endpoint
        # would, instead of passing vacuously.  An expired session or a §19
        # poison owns the reason (the native start_flush reads sess_fail the
        # same way).
        dead_dirty = [c for c in candidates if (not c.alive) and c.dirty]
        if dead_dirty:
            reason = next(
                (c.sess_fail_reason for c in dead_dirty
                 if getattr(c, "sess_fail_reason", None)),
                REASON_NOT_CONNECTED + " (peer reset before flush)")
            if fail is not None:
                fires.append(lambda f=fail, r=reason: f(r))
            return
        targets = [c for c in candidates if c.alive]
        rec = FlushRec(done, fail)
        for c in targets:
            rec.waits[c] = c.alloc_flush_seq()
            grp = getattr(c, "stripe", None)
            if grp is not None and grp.has_unsacked(grp.next_msg_id - 1):
                rec.stripe_waits[c] = grp.next_msg_id - 1
        self.flush_records.append(rec)
        for c in targets:
            c.send_flush(rec.waits[c], fires)
        self._try_complete_flush(rec, fires)
        if timeout is not None and not rec.completed:
            self._add_timer(timeout, lambda fires, r=rec: self._expire_flush(r, fires))

    def _on_flush_ack(self, conn, seq: int, fires) -> None:
        conn.flush_acked = max(conn.flush_acked, seq)
        if hasattr(conn, "on_flush_acked"):
            conn.on_flush_acked(seq)
        for rec in list(self.flush_records):
            self._try_complete_flush(rec, fires)

    def _on_stripe_sack(self, conn, fires) -> None:
        """A striped source was SACKed: barriers waiting on it may now
        complete (core/lane.py RailGroup.on_sack)."""
        for rec in list(self.flush_records):
            self._try_complete_flush(rec, fires)

    def _try_complete_flush(self, rec: FlushRec, fires) -> None:
        if rec.completed:
            return
        pending = [c for c, s in rec.waits.items() if c.flush_acked < s]
        dead = [c for c in pending if not c.alive]
        for c, watermark in rec.stripe_waits.items():
            grp = getattr(c, "stripe", None)
            if grp is not None and grp.has_unsacked(watermark):
                (pending if c.alive else dead).append(c)
        if dead:
            rec.completed = True
            if rec in self.flush_records:
                self.flush_records.remove(rec)
            # A session that expired (rather than a bare reset) owns the
            # failure reason: "session expired" instead of "not connected".
            reason = next(
                (c.sess_fail_reason for c in dead
                 if getattr(c, "sess_fail_reason", None)),
                REASON_NOT_CONNECTED + " (peer reset during flush)")
            if rec.fail is not None:
                fires.append(lambda f=rec.fail, r=reason: f(r))
        elif not pending:
            rec.completed = True
            if rec in self.flush_records:
                self.flush_records.remove(rec)
            self.counters.flushes_completed += 1
            us = int((time.perf_counter() - rec.born) * 1e6)
            self.hists.flush_us[swtrace.hist_bucket(us)] += 1  # §25
            if rec.done is not None:
                fires.append(rec.done)

    # ----------------------------------------------------------- conn mgmt
    def _register_conn_io(self, conn: TcpConn) -> None:
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if conn._want_write else 0)
        self.selector.register(
            conn.sock, events, lambda mask, fires, c=conn: self._on_conn_io(c, mask, fires)
        )
        conn._registered = True

    def _update_conn_interest(self, conn: TcpConn) -> None:
        if not conn._registered or self.selector is None:
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if conn._want_write else 0)
        try:
            self.selector.modify(
                conn.sock, events, lambda mask, fires, c=conn: self._on_conn_io(c, mask, fires)
            )
        except (KeyError, ValueError, OSError):
            pass

    def _unregister_conn_io(self, conn: TcpConn) -> None:
        if getattr(conn, "_registered", False) and self.selector is not None:
            try:
                self.selector.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn._registered = False

    def _on_conn_io(self, conn: TcpConn, mask, fires) -> None:
        if mask & selectors.EVENT_WRITE:
            conn.on_writable(fires)
        if mask & selectors.EVENT_READ and conn.alive:
            conn.on_readable(fires)

    def _conn_broken(self, conn, fires) -> None:
        """Peer died / stream reset.  Pending posted receives stay pending
        (the reference's UCX workers never fail posted recvs on peer death;
        pinned by tests/test_basic.py:250-277) -- only flush barriers
        targeting the connection fail.

        With a live session (STARWAY_SESSION negotiated via "sess"), the
        conn SUSPENDS instead: queues/journal/flush bookkeeping survive,
        the client redials under backoff, and in-flight ops complete late
        after the resume replay (DESIGN.md §14).  Only session expiry
        (grace elapsed / epoch mismatch) falls back to failure, with the
        stable "session expired" reason.

        With liveness detection active (STARWAY_KEEPALIVE > 0) on a
        ka-negotiated conn, the user has opted out of recvs-pend-forever:
        whatever killed the conn (liveness expiry, RST, EOF), the receive
        it was streaming into fails, and once no alive conns remain every
        queued receive fails too -- stable "not connected" keyword."""
        was_alive = conn.alive
        if self._trace is not None and conn.alive:
            self._trace.rec(swtrace.EV_CONN_DOWN, 0, conn.conn_id)
        sess = getattr(conn, "sess", None)
        if (sess is not None and conn.alive and not sess.expired
                and not sess.suspended):
            with self.lock:
                running = self.status == state.RUNNING
            if running:
                self._sess_suspend(conn, fires)
                return
        if was_alive and getattr(conn, "_proto", None) is not None:
            # swrefine: terminal transport death (the suspend path above
            # records "lost" instead; DESIGN.md §22).
            conn._proto.rec(swtrace.EV_PROTO, 0, conn.conn_id, 0, "down")
        ka_live = (self._ka_interval > 0 and conn.alive
                   and getattr(conn, "ka_ok", False))
        stranded = None
        if ka_live:
            with self.lock:
                msg = getattr(conn, "_rx_msg", None)
                if msg is not None and msg.posted is not None and not msg.complete:
                    stranded = msg.posted
                    msg.posted = None  # mark_dead's purge drops the partial
        conn.mark_dead(fires)
        root = getattr(conn, "rail_parent", None)
        if root is not None:
            # A secondary lane died: the endpoint survives.  Its
            # claimed-but-unacked chunks re-queue onto the surviving
            # lanes (core/lane.py rail_lost; ``rail_resteals``).
            root.rails = [r for r in root.rails if r is not conn]
            if root.alive and root.stripe is not None:
                root.stripe.rail_lost(conn, fires)
        for r in list(getattr(conn, "rails", ())):
            # The primary died terminally: its rails are meaningless.
            if r.alive:
                self._conn_broken(r, fires)
        if ka_live:
            reason = REASON_NOT_CONNECTED + " (peer lost; liveness detection active)"
            if stranded is not None and stranded.fail is not None:
                fires.append(lambda f=stranded.fail, r=reason: f(r))
            with self.lock:
                if not any(c.alive for c in self.conns.values()):
                    fires.extend(self.matcher.fail_pending(reason))
        # Unclaimed, unstarted pull descriptors from the dead peer can never
        # resolve: drop them (a claimed one keeps its receive pending, the
        # peer-death contract; a started pull resolves on its own).
        remote_msgs = getattr(conn, "_remote_msgs", None)
        if remote_msgs:
            with self.lock:
                for msg in list(remote_msgs):
                    if msg.posted is None and not msg.remote.started:
                        msg.discard = True
                        try:
                            self.matcher.unexpected.remove(msg)
                        except ValueError:
                            pass
                        remote_msgs.discard(msg)
        getattr(self, "_half_open", set()).discard(conn)
        for rec in list(self.flush_records):
            self._try_complete_flush(rec, fires)

    # ------------------------------------------------------------- session
    @staticmethod
    def _sess_int(v) -> int:
        """Peer-supplied session integers (sess_ack) arrive as JSON
        strings; a malformed value must not raise on the engine thread
        (one bad handshake would emergency-close the whole worker).
        Junk parses as 0 -- replay everything, the receiver's dedup
        absorbs it (the C++ engine's strtoull does the same)."""
        try:
            return int(str(v))
        except (TypeError, ValueError):
            return 0

    def _sess_suspend(self, conn, fires) -> None:
        """A session-enabled conn lost its transport: suspend instead of
        cancelling.  The client side redials under backoff; the server
        side waits for the peer's resume dial; either side expires the
        session once the grace window elapses."""
        logger.warning(
            "starway: conn %s lost; session %s suspended (grace %.3gs)",
            conn.conn_id, conn.sess.sid[:8], conn.sess.grace)
        conn.suspend(fires)
        for r in list(getattr(conn, "rails", ())):
            # Rails are per-incarnation transports (like sm rings): the
            # resumed client re-dials them; un-SACKed striped sources
            # re-dispatch wholesale at resume (journal per-message).
            if r.alive:
                self._conn_broken(r, fires)
        self._add_timer(conn.sess.grace,
                        lambda fires, c=conn: self._sess_check_grace(c, fires))
        if self.kind == "client":
            self._add_timer(0.01,
                            lambda fires, c=conn: self._sess_redial(c, fires))

    def _sess_check_grace(self, conn, fires) -> None:
        sess = conn.sess
        if sess is None or sess.expired or not sess.suspended:
            return
        if time.monotonic() >= sess.deadline:
            self._sess_expire(conn, fires)

    def _sess_expire(self, conn, fires) -> None:
        """Terminal session failure: grace elapsed, or the peer answered a
        resume dial with a new epoch.  Everything that was riding out the
        outage fails with the stable "session expired" reason."""
        sess = conn.sess
        if sess is None or sess.expired:
            return
        sess.expired = True
        reason = REASON_SESSION_EXPIRED
        conn.sess_fail_reason = reason
        logger.warning("starway: session %s expired", sess.sid[:8])
        if self._trace is not None:
            self._trace.rec(swtrace.EV_SESS_EXPIRE, 0, conn.conn_id, 0, reason)
        if getattr(conn, "_proto", None) is not None:
            # swrefine: terminal expiry -- from `suspended` (grace
            # elapsed / epoch mismatch) or straight from `estab` (the
            # stale-epoch registration path, MONITOR_EXTRA in
            # analysis/refine.py; DESIGN.md §22).
            conn._proto.rec(swtrace.EV_PROTO, 0, conn.conn_id, 0, "expire")
        self._faulted = True
        swtrace.flight_dump("session-expired", self, reason)
        # count=True: the C++ engine bumps ops_cancelled per item it fails
        # at expiry (sess_cancel_terminal) -- the cross-engine counter
        # registry must agree for identical wire histories.
        conn._cancel_tx_state(fires, reason, count=True)
        conn.mark_dead(fires)
        getattr(self, "_sessions", {}).pop(sess.sid, None)
        # Session users opted into bounded failure (like the keepalive
        # contract): queued receives fail once no alive conns remain.
        with self.lock:
            if not any(c.alive for c in self.conns.values()):
                fires.extend(self.matcher.fail_pending(reason))
        for rec in list(self.flush_records):
            self._try_complete_flush(rec, fires)

    # --------------------------------------------------------------- hooks
    def _on_hello(self, conn, info, fires) -> None:  # pragma: no cover - server only
        pass

    def _on_hello_ack(self, conn, info, fires) -> None:  # pragma: no cover
        pass

    # --------------------------------------------------------------- close
    def _do_close(self) -> None:
        fires: list = []
        _fail_idx = {"send": 5, "devpull": 4, "flush": 2}
        with self.lock:
            while self.ops:
                op = self.ops.popleft()
                idx = _fail_idx.get(op[0])
                fail = op[idx] if idx is not None else None
                if fail is not None:
                    self.counters.ops_cancelled += 1
                    fires.append(lambda f=fail: f(REASON_CANCELLED))
            fires.extend(self.matcher.cancel_all())
            conns = list(self.conns.values())
            mgr, self._xfer_mgr = self._xfer_mgr, None
        if mgr is not None:
            # Dropping the transfer server cancels unpulled offers (the
            # close-cancels-in-flight contract for device sends).
            mgr.close()
        for rec in self.flush_records:
            if not rec.completed and rec.fail is not None:
                self.counters.ops_cancelled += 1
                fires.append(lambda f=rec.fail: f(REASON_CANCELLED))
        self.flush_records.clear()
        for c in conns:
            c.close(fires)
        for c in list(getattr(self, "_half_open", ())):
            c.mark_dead(fires)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        fabric.unregister(self)
        try:
            if self.selector is not None:
                self.selector.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        with self.lock:
            self.status = state.CLOSED
            cb = self.close_cb
            self.close_cb = None
        _run_fires(fires)
        # Park the ring's final contents for post-close consumers (bench
        # --trace reports run after the workers are gone).
        swtrace.retire(self)
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("starway: close callback raised")

    def _teardown_sockets(self) -> None:
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            if self.selector is not None:
                self.selector.close()
        except OSError:
            pass


class ClientWorker(Worker):
    """Engine behind ``starway_tpu.Client`` (reference: struct Client,
    src/bindings/main.hpp:131-189; connect-once lifecycle main.cpp:552-585)."""

    kind = "client"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.primary_conn = None
        self._connect_cb = None
        self._connect_target = None
        self._connect_timeout: Optional[float] = None
        self._sess_target: Optional[tuple] = None  # (addr, port) for redials

    def connect(self, addr: str, port: int, cb,
                timeout: Optional[float] = None) -> None:
        with self.lock:
            if self.status != state.VOID:
                raise StarwayStateError(
                    "starway client supports a single connect "
                    f"(status={state.NAMES[self.status]})"
                )
            self.status = state.INIT
        self._connect_cb = cb
        self._connect_timeout = timeout
        self._connect_target = ("socket", addr, port, None)
        self._start_thread()

    def connect_address(self, blob: bytes, cb,
                        timeout: Optional[float] = None) -> None:
        info = frames.unpack_json_body(blob)
        with self.lock:
            if self.status != state.VOID:
                raise StarwayStateError(
                    "starway client supports a single connect "
                    f"(status={state.NAMES[self.status]})"
                )
            self.status = state.INIT
        self._connect_cb = cb
        self._connect_timeout = timeout
        self._connect_target = (
            "address",
            info.get("host", "127.0.0.1"),
            int(info.get("port", 0)),
            info.get("worker_id"),
        )
        self._start_thread()

    def _fail_connect(self, cb, reason: str) -> None:
        with self.lock:
            self.status = state.CLOSED
        self._teardown_sockets()
        if cb is not None:
            _run_fires([lambda: cb(reason)])

    def _setup(self) -> bool:
        mode, addr, port, wid = self._connect_target
        cb = self._connect_cb
        if config.inproc_enabled():
            target = fabric.lookup_worker_id(wid) if wid else fabric.lookup_sockaddr(addr, port)
            if target is not None and target is not self:
                try:
                    conn = target.attach_inproc(self, mode)
                except Exception as e:
                    self._fail_connect(cb, f"{REASON_NOT_CONNECTED}: {e}")
                    return False
                self.primary_conn = conn
                with self.lock:
                    self.conns[conn.conn_id] = conn
                    if self.status == state.INIT:
                        self.status = state.RUNNING
                fabric.register_worker(self)
                if self._trace is not None:
                    self._trace.rec(swtrace.EV_CONN_UP, 0, conn.conn_id)
                if cb is not None:
                    _run_fires([lambda: cb("")])
                return True
        # Real TCP path (cross-process / DCN bootstrap).  The HELLO offers a
        # same-host shared-memory upgrade when enabled; a peer that mapped
        # the segment confirms with "sm": "ok" (core/shmring.py).  A
        # session offer (STARWAY_SESSION) disables the sm upgrade: the
        # rings are a per-incarnation transport with no replay journal.
        sess_on = config.session_enabled()
        self._sess_target = (addr, port)
        sm_offer = None
        if config.sm_enabled() and not sess_on:
            try:
                from . import shmring

                sm_offer = shmring.ShmSegment.create(self.worker_id[:8])
            except Exception:
                sm_offer = None
        connect_timeout = self._connect_timeout or config.connect_timeout()
        try:
            extra = {"ka": "ok"}  # liveness capability, always offered
            # swscope end-to-end stitching (DESIGN.md §15): with tracing
            # armed, offer a fresh trace-conn id; a tracing acceptor
            # confirms with "tr": "ok" and both rings tag EV_E2E events
            # with it.
            tr_offer = ""
            if self._trace is not None:
                tr_offer = uuid.uuid4().hex[:16]
                extra["tr"] = tr_offer
            rails_n = config.stripe_rails()
            if rails_n > 1:
                # Multi-rail striping offer (DESIGN.md §17): a capable
                # acceptor confirms "rails": "ok" and we dial the extra
                # lanes right after the primary handshake.
                extra["rails"] = str(rails_n)
            fc_w = config.fc_window()
            if fc_w > 0:
                # Receiver-driven flow control offer (DESIGN.md §18):
                # the value is OUR unexpected-queue budget for the
                # peer's eager traffic; an fc-capable acceptor answers
                # with its own window.
                extra["fc"] = str(fc_w)
            integ = config.integrity_enabled()
            if integ:
                # End-to-end integrity offer (DESIGN.md §19): an
                # integrity-capable acceptor confirms "csum": "ok" and
                # every later frame on the conn is checksummed.
                extra["csum"] = "1"
            if sess_on:
                # Stable session id + epoch 0 (the acceptor assigns the
                # real epoch); sess_ack is our cumulative rx seq (0 new).
                extra.update(sess="ok", sess_id=self.worker_id,
                             sess_epoch="0", sess_ack="0")
            if sm_offer is not None:
                extra.update(
                    sm_key=sm_offer.key,
                    sm_nonce=f"{sm_offer.nonce:016x}",
                    sm_ring=str(sm_offer.ring_size),
                )
            from .. import device as _device

            if _device.devpull_supported():
                extra["devpull"] = "ok"
            sock = socket.create_connection((addr, port), timeout=connect_timeout)
            sock.settimeout(connect_timeout)
            sock.sendall(frames.pack_hello(self.worker_id, mode, self.name, extra))
            hdr = _read_exact(sock, frames.HEADER_SIZE)
            ftype, _, blen = frames.unpack_header(hdr)
            if ftype != frames.T_HELLO_ACK:
                raise ConnectionError("unexpected frame during handshake")
            ack = frames.unpack_json_body(_read_exact(sock, blen))
        except Exception as e:
            if sm_offer is not None:
                sm_offer.unlink()
                sm_offer.close()
            self._fail_connect(cb, f"{REASON_NOT_CONNECTED}: {e}")
            return False
        conn = TcpConn(self, sock, mode, handshaken=True)
        conn.peer_name = ack.get("worker_id", "")
        conn.devpull_ok = ack.get("devpull") == "ok"
        conn.ka_ok = ack.get("ka") == "ok"
        conn.rails_ok = rails_n > 1 and ack.get("rails") == "ok"
        if fc_w > 0 and self._sess_int(ack.get("fc", 0)) > 0:
            conn.fc_ok = True
            conn.fc_window = conn.fc_credits = self._sess_int(ack["fc"])
        conn.csum_ok = integ and ack.get("csum") == "ok"
        if tr_offer and ack.get("tr") == "ok":
            conn.tr_id = tr_offer
        if sess_on and ack.get("sess") == "ok":
            conn.sess = SessionState(self.worker_id,
                                     str(ack.get("sess_epoch", "")))
        if sm_offer is not None:
            if ack.get("sm") == "ok":
                conn.adopt_sm(sm_offer, creator=True)
                if conn.csum_ok:
                    # §19: the rings carry checksummed slot records from
                    # the first byte (both sides enable at handshake).
                    sm_offer.enable_integrity()
            else:
                sm_offer.unlink()
                sm_offer.close()
        self.primary_conn = conn
        with self.lock:
            self.conns[conn.conn_id] = conn
            if self.status == state.INIT:
                self.status = state.RUNNING
        self._register_conn_io(conn)
        fabric.register_worker(self)
        if conn._proto is not None:
            # swrefine: the blocking handshake above IS the hello-sent
            # state -- HELLO written, HELLO_ACK consumed synchronously
            # before the conn object exists, so both events are recorded
            # here at its birth (DESIGN.md §22).
            conn._proto.rec(swtrace.EV_PROTO, 0, conn.conn_id, 0,
                            "st:hello-sent")
            conn._proto.rec(swtrace.EV_PROTO, 0, conn.conn_id, 0,
                            "rx:HELLO_ACK")
        if conn.rails_ok:
            self._dial_rails(conn, addr, port, rails_n - 1)
        if self._trace is not None:
            self._trace.rec(swtrace.EV_CONN_UP, 0, conn.conn_id)
        if conn.tr_id:
            # One-shot clock exchange at handshake (engine thread, before
            # the loop): a timestamped PING whose PONG yields the first
            # EV_CLOCK sample, so trace --merge can align this process's
            # ring with the peer's even when keepalive never fires.
            ping_fires: list = []
            conn.send_ping(ping_fires)
            _run_fires(ping_fires)
        if cb is not None:
            _run_fires([lambda: cb("")])
        return True

    # --------------------------------------------------------------- rails
    def _dial_rails(self, primary, addr: str, port: int, count: int) -> None:
        """Open ``count`` secondary lanes to the accepted endpoint
        (DESIGN.md §17).  Blocking dials on the engine thread, like the
        primary handshake; a failed rail is skipped -- striping simply
        runs over fewer lanes."""
        timeout = self._connect_timeout or config.connect_timeout()
        fires: list = []
        for i in range(count):
            sock = None
            try:
                sock = socket.create_connection((addr, port), timeout=timeout)
                sock.settimeout(timeout)
                extra = {"rail_of": self.worker_id, "rail_idx": str(i + 1),
                         "ka": "ok"}
                if config.integrity_enabled():
                    # §19: every lane of a railed conn checksums its own
                    # frames (chunks verify on the rail they rode).
                    extra["csum"] = "1"
                sock.sendall(frames.pack_hello(self.worker_id, "socket",
                                               self.name, extra))
                hdr = _read_exact(sock, frames.HEADER_SIZE)
                ftype, _, blen = frames.unpack_header(hdr)
                if ftype != frames.T_HELLO_ACK:
                    raise ConnectionError("unexpected frame during rail handshake")
                ack = frames.unpack_json_body(_read_exact(sock, blen))
                if ack.get("rail") != "ok":
                    raise ConnectionError("peer refused rail attach")
            except Exception as e:
                logger.warning("starway: rail %d dial failed (%s); striping "
                               "continues over fewer lanes", i + 1, e)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                continue
            rail = TcpConn(self, sock, "socket", handshaken=True)
            rail.peer_name = primary.peer_name
            rail.ka_ok = ack.get("ka") == "ok"
            rail.csum_ok = (config.integrity_enabled()
                            and ack.get("csum") == "ok")
            primary.attach_rail(rail, fires)
            with self.lock:
                self.conns[rail.conn_id] = rail
            self._register_conn_io(rail)
            if rail._proto is not None:
                # swrefine: rails take the same blocking handshake as the
                # primary (DESIGN.md §22).
                rail._proto.rec(swtrace.EV_PROTO, 0, rail.conn_id, 0,
                                "st:hello-sent")
                rail._proto.rec(swtrace.EV_PROTO, 0, rail.conn_id, 0,
                                "rx:HELLO_ACK")
            if self._trace is not None:
                self._trace.rec(swtrace.EV_CONN_UP, 0, rail.conn_id)
        _run_fires(fires)

    # ------------------------------------------------------ session redial
    def _sess_redial(self, conn, fires) -> None:
        """One resume attempt for a suspended session (engine thread;
        scheduled by _sess_suspend and re-armed under exponential backoff
        with jitter -- the PR-1 reconnect shape, now transparent)."""
        sess = conn.sess
        with self.lock:
            running = self.status == state.RUNNING
        if not running or sess is None or sess.expired or not sess.suspended:
            return
        if time.monotonic() >= sess.deadline:
            self._sess_expire(conn, fires)
            return
        addr, port = self._sess_target
        try:
            sock, ack = self._sess_dial(addr, port, sess)
        except Exception as e:
            # NOT counted in swtrace.GLOBAL.reconnects: that counter is
            # api-layer aconnect retries, and the native engine's redial
            # path has no equivalent hook -- bumping it here would break
            # cross-engine counter parity for identical outages.
            delay = sess.redial_delay() * (0.5 + random.random() / 2)
            logger.debug("starway: session redial failed (%s); retry in %.3gs",
                         e, delay)
            self._add_timer(delay,
                            lambda fires, c=conn: self._sess_redial(c, fires))
            return
        if (ack.get("sess") != "ok"
                or str(ack.get("sess_epoch", "")) != sess.epoch):
            # The peer restarted (or forgot us): a new epoch is a new
            # session -- ours is expired, not resumable.
            try:
                sock.close()
            except OSError:
                pass
            self._sess_expire(conn, fires)
            return
        conn.resume(sock, self._sess_int(ack.get("sess_ack", "0")), fires)
        if conn.rails_ok:
            # Rails are per-incarnation: re-dial them now that the
            # session is back (striped sources already re-dispatched on
            # the primary; new lanes start stealing as they attach).
            self._dial_rails(conn, addr, port, config.stripe_rails() - 1)

    def _sess_dial(self, addr: str, port: int, sess) -> tuple:
        """One blocking resume dial + handshake (bounded by the connect
        timeout; the engine thread sleeps in backoff between attempts).
        Returns (socket, parsed HELLO_ACK dict); raises on failure."""
        timeout = self._connect_timeout or config.connect_timeout()
        extra = {"ka": "ok", "sess": "ok", "sess_id": sess.sid,
                 "sess_epoch": sess.epoch, "sess_ack": str(sess.rx_cum)}
        if config.integrity_enabled():
            # §19: re-offered per incarnation for wire-format consistency
            # (csum_ok is sticky on the session conn either way).
            extra["csum"] = "1"
        if config.fc_window() > 0:
            # Fresh credit window per incarnation (DESIGN.md §18): both
            # sides reset to their stored windows at resume; the key is
            # re-advertised for wire-format consistency.
            extra["fc"] = str(config.fc_window())
        from .. import device as _device

        if _device.devpull_supported():
            extra["devpull"] = "ok"
        mode = self._connect_target[0] if self._connect_target else "socket"
        sock = socket.create_connection((addr, port), timeout=timeout)
        try:
            sock.settimeout(timeout)
            sock.sendall(frames.pack_hello(self.worker_id, mode, self.name,
                                           extra))
            hdr = _read_exact(sock, frames.HEADER_SIZE)
            ftype, _, blen = frames.unpack_header(hdr)
            if ftype != frames.T_HELLO_ACK:
                raise ConnectionError("unexpected frame during session resume")
            ack = frames.unpack_json_body(_read_exact(sock, blen))
        except Exception:
            try:
                sock.close()
            except OSError:
                pass
            raise
        return sock, ack


class ServerWorker(Worker):
    """Engine behind ``starway_tpu.Server`` (reference: struct Server,
    src/bindings/main.hpp:306-376; listen modes main.cpp:811-851,1063-1124)."""

    kind = "server"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.accept_cb = None
        self.eps: dict = {}  # conn_id -> ServerEndpoint
        # Accepted TCP conns whose HELLO has not arrived yet; they join
        # self.conns at handshake and must still be torn down at close.
        self._half_open: set = set()
        # Resilient sessions: sess_id -> conn (suspended conns wait here
        # for the peer's resume dial; see _sess_hello / DESIGN.md §14).
        self._sessions: dict = {}

    def set_accept_cb(self, cb) -> None:
        self.accept_cb = cb

    def listen(self, addr: str, port: int) -> None:
        with self.lock:
            if self.status != state.VOID:
                raise StarwayStateError(
                    f"starway server already listening or closed (status={state.NAMES[self.status]})"
                )
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                listener.bind((addr, port))
                listener.listen(512)
            except OSError:
                listener.close()
                raise
            listener.setblocking(False)
            self._listener = listener
            self.mode = "socket"
            self.status = state.RUNNING
            # Use the kernel-assigned port so listen(addr, 0) advertises a
            # connectable address.
            bound_port = listener.getsockname()[1]
            self._make_address_blob(addr, bound_port)
        fabric.register(self, addr, bound_port)
        self._start_thread()

    def listen_address(self) -> bytes:
        """Worker-address (listenerless in the reference) bootstrap mode.

        The reference returns serialized UCX worker-address bytes and relies
        on an out-of-band channel to move them (src/bindings/main.cpp:834-860).
        Here the blob carries the worker id plus host:port contact info; an
        in-process peer attaches directly through the fabric registry and a
        cross-process peer bootstraps over TCP (the DCN analogue).
        """
        with self.lock:
            if self.status != state.VOID:
                raise StarwayStateError(
                    f"starway server already listening or closed (status={state.NAMES[self.status]})"
                )
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(("0.0.0.0", 0))
            listener.listen(512)
            listener.setblocking(False)
            self._listener = listener
            self.mode = "address"
            self.status = state.RUNNING
            self._make_address_blob(config.advertised_host(), listener.getsockname()[1])
        fabric.register_worker(self)
        self._start_thread()
        return self._address_blob

    def _make_address_blob(self, host: str, port: int) -> None:
        self._address_blob = json.dumps(
            {
                "worker_id": self.worker_id,
                "host": host if host not in ("0.0.0.0", "") else config.advertised_host(),
                "port": port,
                "fabric": "starway-tpu",
            }
        ).encode()

    def _setup(self) -> bool:
        self.selector.register(self._listener, selectors.EVENT_READ, self._on_accept)
        return True

    def _on_accept(self, mask, fires) -> None:
        while True:
            try:
                s, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            conn = TcpConn(self, s, "socket", handshaken=False)
            if conn._proto is not None:
                # swrefine: accepted conns start in `estab` -- the
                # pre-HELLO accept state is folded into the same framed
                # dispatch (DESIGN.md §16, §22).
                conn._proto.rec(swtrace.EV_PROTO, 0, conn.conn_id, 0,
                                "st:estab")
            self._half_open.add(conn)
            self._register_conn_io(conn)
            # The connection joins self.conns once its HELLO arrives.

    def _on_hello(self, conn, info, fires) -> None:
        conn.peer_name = info.get("worker_id", "")
        mode = info.get("mode", "socket")
        conn.mode = mode
        if mode == "address":
            # Mirrors the reference: in worker-address mode endpoint socket
            # fields are empty (README.md:141-143).
            conn.local_addr = conn.remote_addr = ""
            conn.local_port = conn.remote_port = 0
        conn.handshaken = True
        self._half_open.discard(conn)
        if info.get("rail_of"):
            # Secondary-lane attach (DESIGN.md §17): adopt the conn into
            # the existing endpoint's rail set -- no new ServerEndpoint,
            # no accept callback, no sm/session negotiation.
            self._on_rail_hello(conn, str(info["rail_of"]), info, fires)
            return
        # Resilient-session handshake (config.py STARWAY_SESSION): a
        # resume dial adopts the new socket into the suspended conn; a
        # fresh offer registers a new session.  Session conns never take
        # the sm upgrade (the rings are per-incarnation, no replay).
        sess_offered = (config.session_enabled()
                        and info.get("sess") == "ok" and "sess_id" in info)
        if sess_offered and self._sess_hello(conn, info, fires):
            return  # resumed onto the suspended conn; this wrapper consumed
        # §19 integrity negotiation, decided BEFORE the sm adopt below:
        # the rings' slot-record framing must be agreed before any ring
        # byte flows.
        csum_on = config.integrity_enabled() and bool(info.get("csum"))
        conn.csum_ok = csum_on
        # Same-host shared-memory offer: map + validate the segment, confirm
        # in the ACK.  Any failure (different host, bad nonce, sm disabled)
        # silently stays on TCP.
        sm_seg = None
        if config.sm_enabled() and "sm_key" in info and not sess_offered:
            try:
                from . import shmring

                sm_seg = shmring.ShmSegment.attach(
                    str(info["sm_key"]),
                    int(str(info.get("sm_nonce", "0")), 16),
                    int(str(info.get("sm_ring", "0"))),
                )
            except Exception:
                sm_seg = None
        # Settle the transport before the endpoint becomes visible, but
        # register before the ACK goes out: by the time the client's connect
        # completes, list_clients() must already contain it.
        if sm_seg is not None:
            conn.adopt_sm(sm_seg, creator=False, defer_tx=True)
            if csum_on:
                sm_seg.enable_integrity()
        ep = ServerEndpoint(conn)
        with self.lock:
            self.conns[conn.conn_id] = conn
            self.eps[conn.conn_id] = ep
        ack_extra = {}
        if sm_seg is not None:
            ack_extra["sm"] = "ok"
        if info.get("ka") == "ok":
            # Liveness capability negotiated: both sides may PING and both
            # must PONG (activation stays per-process via STARWAY_KEEPALIVE).
            conn.ka_ok = True
            ack_extra["ka"] = "ok"
        if info.get("rails"):
            # Multi-rail striping capability: the connector will dial the
            # extra lanes (rail_of) right after this ACK.
            conn.rails_ok = True
            ack_extra["rails"] = "ok"
        fc_w = config.fc_window()
        if fc_w > 0 and self._sess_int(info.get("fc", 0)) > 0:
            # Receiver-driven flow control (DESIGN.md §18): adopt the
            # connector's advertised window for OUR sends, answer with
            # our own for its sends.
            conn.fc_ok = True
            conn.fc_window = conn.fc_credits = self._sess_int(info["fc"])
            ack_extra["fc"] = str(fc_w)
        if csum_on:
            ack_extra["csum"] = "ok"
        if self._trace is not None and info.get("tr"):
            # swscope stitching: adopt the connector's trace-conn id so
            # both rings tag this conn's EV_E2E events identically.
            conn.tr_id = str(info["tr"])
            ack_extra["tr"] = "ok"
        from .. import device as _device

        if info.get("devpull") == "ok" and _device.devpull_supported():
            conn.devpull_ok = True
            ack_extra["devpull"] = "ok"
        if sess_offered:
            ack_extra.update(sess="ok", sess_epoch=conn.sess.epoch,
                             sess_ack="0")
        # The ACK is the transport switch point: marking it routes anything
        # queued behind it (e.g. sends from the accept callback) to the ring
        # even while the ACK itself is still draining to the socket.
        conn.send_ctl(frames.pack_hello_ack(self.worker_id, ack_extra or None),
                      fires, switch_after=sm_seg is not None)
        if self._trace is not None:
            self._trace.rec(swtrace.EV_CONN_UP, 0, conn.conn_id)
        if self.accept_cb is not None:
            fires.append(lambda ep=ep: self.accept_cb(ep))

    def _on_rail_hello(self, conn, rail_of: str, info, fires) -> None:
        """Attach an accepted conn as a secondary lane of the endpoint
        whose peer worker id is ``rail_of`` (the primary handshake
        confirmed ``"rails": "ok"`` moments earlier)."""
        primary = None
        with self.lock:
            for c in self.conns.values():
                if (c.kind == "tcp" and c.alive and c.handshaken
                        and c.peer_name == rail_of
                        and getattr(c, "rail_parent", None) is None):
                    primary = c
                    break
        if primary is None:
            # Raced the endpoint's death (or a bogus attach): answer
            # without "rail": "ok"; the dialer drops the socket.
            conn.send_ctl(frames.pack_hello_ack(self.worker_id, None), fires)
            return
        ack_extra = {"rail": "ok"}
        if info.get("ka") == "ok":
            conn.ka_ok = True
            ack_extra["ka"] = "ok"
        if config.integrity_enabled() and info.get("csum"):
            conn.csum_ok = True
            ack_extra["csum"] = "ok"
        with self.lock:
            self.conns[conn.conn_id] = conn
        # ACK first: attach_rail may dispatch a feeder and kick TX at
        # once (mid-stripe join), and SDATA bytes ahead of the HELLO_ACK
        # would make the dialer reject the rail (native on_rail_hello
        # has the same order).
        conn.send_ctl(frames.pack_hello_ack(self.worker_id, ack_extra), fires)
        primary.attach_rail(conn, fires)
        if self._trace is not None:
            self._trace.rec(swtrace.EV_CONN_UP, 0, conn.conn_id)

    def _sess_hello(self, conn, info, fires) -> bool:
        """Session half of the accept handshake.  Returns True when this
        dial RESUMED an existing suspended session (``conn`` -- the fresh
        accept wrapper -- was consumed: its socket moved onto the
        suspended conn); False when a new session was registered on
        ``conn`` and the normal accept path continues."""
        sid = str(info["sess_id"])
        req_epoch = str(info.get("sess_epoch", "0"))
        existing = self._sessions.get(sid)
        if (existing is not None and existing.sess is not None
                and not existing.sess.expired
                and existing.sess.epoch == req_epoch):
            if not existing.sess.suspended:
                # One-sided failure: the client saw its conn die and
                # redialed before this side noticed (no EOF yet, ka not
                # expired).  The resume dial itself proves the old
                # incarnation dead -- supersede it instead of expiring a
                # perfectly resumable session.
                self._sess_suspend(existing, fires)
            peer_ack = self._sess_int(info.get("sess_ack", "0"))
            self._unregister_conn_io(conn)
            sock, conn.sock = conn.sock, None
            conn.alive = False  # wrapper never entered self.conns
            ack_extra = {"sess": "ok", "sess_epoch": existing.sess.epoch,
                         "sess_ack": str(existing.sess.rx_cum)}
            if existing.ka_ok:
                ack_extra["ka"] = "ok"
            if existing.csum_ok:
                ack_extra["csum"] = "ok"
            if existing.devpull_ok:
                ack_extra["devpull"] = "ok"
            if existing.fc_ok:
                ack_extra["fc"] = str(config.fc_window() or
                                      existing.fc_window)
            existing.resume(
                sock, peer_ack, fires,
                ack_ctl=frames.pack_hello_ack(self.worker_id, ack_extra))
            return True
        if existing is not None and existing is not conn:
            # Same session id, stale epoch: the old incarnation can never
            # resume -- expire it before the new registration shadows it
            # in the registry.
            self._sess_expire(existing, fires)
        # New session: the acceptor assigns the epoch; a resuming client
        # that lands here sees the mismatch and expires its session.
        conn.sess = SessionState(sid, uuid.uuid4().hex[:8])
        self._sessions[sid] = conn
        return False

    def attach_inproc(self, client_worker, mode: str):
        """Attach a same-process client (called from the client's engine
        thread).  The analogue of the reference's reverse-endpoint creation in
        the AM handshake path (src/bindings/main.cpp:912-938) -- except the
        in-process conn pair is naturally full-duplex, so no reverse endpoint
        is needed."""
        server_side = InprocConn(self, weakref.ref(client_worker), mode)
        client_side = InprocConn(client_worker, weakref.ref(self), mode)
        server_side.peer_conn = client_side
        client_side.peer_conn = server_side
        server_side.peer_name = client_worker.worker_id
        client_side.peer_name = self.worker_id
        if mode == "socket" and self._listener is not None:
            try:
                la, lp = self._listener.getsockname()[:2]
                server_side.local_addr, server_side.local_port = la, lp
                server_side.remote_addr = "127.0.0.1"
            except OSError:
                pass
        ep = ServerEndpoint(server_side)
        with self.lock:
            if self.status != state.RUNNING:
                raise StarwayStateError("server is not in a running state")
            self.conns[server_side.conn_id] = server_side
            self.eps[server_side.conn_id] = ep
        if self._trace is not None:
            self._trace.rec(swtrace.EV_CONN_UP, 0, server_side.conn_id)
        if self.accept_cb is not None:
            _run_fires([lambda: self.accept_cb(ep)])
        return client_side

    def list_clients(self) -> set:
        with self.lock:
            return set(self.eps.values())


def _read_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    got = 0
    while got < n:
        r = sock.recv_into(memoryview(buf)[got:])
        if r == 0:
            raise ConnectionError("peer closed during handshake")
        got += r
    return buf
