"""Host-side tag-matching engine.

The reference delegates tag matching to UCX: receives are posted on the
*worker* (any endpoint, fan-in) with a 64-bit tag + mask and matched against
incoming messages by the transport (``ucp_tag_recv_nbx`` with wildcard masks,
reference: src/bindings/main.cpp:404,1172; fan-in behaviour pinned by
tests/test_basic.py:526-554).  TPU interconnects have no tag matching, so the
matcher is a first-class component of the host runtime (SURVEY.md section 7,
"Hard parts").

Matching rule (UCX semantics): a posted receive ``(rtag, rmask)`` matches an
incoming message with tag ``stag`` iff ``(stag & rmask) == (rtag & rmask)``.
``rmask == 0`` is the wildcard used throughout the reference tests
(tests/test_basic.py:547).  Both posted receives and unexpected messages are
kept in FIFO order, matching UCX's ordering guarantees.

Receive targets and payloads are duck-typed so device (jax.Array) transfers
ride the same matcher with no jax dependency here:

* host target: writable ``memoryview``; host payload: ``memoryview``;
* device target: ``DeviceRecvSink`` (``nbytes`` / ``host_staging()`` /
  ``finalize_from_host()`` / ``accept_device()`` / optional
  ``accept_host()`` for complete-bytes-in-hand delivery, see device.py);
* device payload: ``DevicePayload`` (``nbytes`` / ``as_host_view()`` /
  ``.array``).

Threading: the matcher is owned by a Worker and guarded by the worker's lock.
All mutating methods return a list of zero-argument "fire" thunks (completed /
failed user callbacks); the caller must invoke them *after* releasing the
worker lock so user callbacks can re-enter the API without deadlocking.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from ..errors import REASON_CANCELLED, REASON_TIMEOUT, REASON_TRUNCATED
from . import swtrace

DoneCb = Callable[[int, int], None]  # (sender_tag, length)
FailCb = Callable[[str], None]

# Reserved probe tag ("SW_PROBE"): messages sent with this exact tag are
# consumed and dropped by the matcher on arrival -- they never enter the
# unexpected queue and never match a receive, wildcard or not.  This is
# what perf.autocalibrate sends, so live link probing cannot pollute the
# peer's matching state.  The contract is shared with the native engine
# (native/sw_engine.cpp).
PROBE_TAG = 0x53575F50524F4245


def tags_match(stag: int, rtag: int, rmask: int) -> bool:
    return (stag & rmask) == (rtag & rmask)


def _size(target_or_payload) -> int:
    if isinstance(target_or_payload, memoryview):
        return len(target_or_payload)
    return int(target_or_payload.nbytes)


def _is_host(x) -> bool:
    return isinstance(x, memoryview)


class PostedRecv:
    """A receive posted by the application, waiting for a matching message.

    ``buf`` is a writable host memoryview or a DeviceRecvSink.
    """

    # __weakref__: deadline timers (core/engine.py) hold posted receives
    # weakly, so a settled receive's buffer is not pinned until its timer
    # would have fired.
    __slots__ = ("buf", "tag", "mask", "done", "fail", "claimed", "owner",
                 "t_post", "__weakref__")

    def __init__(self, buf, tag: int, mask: int, done: DoneCb, fail: FailCb, owner=None):
        self.buf = buf
        self.tag = tag
        self.mask = mask
        self.done = done
        self.fail = fail
        self.claimed = False  # an in-flight inbound message is streaming to us
        self.owner = owner  # keepalive for the python object owning buf
        self.t_post = time.perf_counter()  # swpulse recv_wait_us origin (§25)

    @property
    def size(self) -> int:
        return _size(self.buf)


class InboundMsg:
    """An inbound message whose header has arrived.

    ``sink`` is the memoryview payload bytes are streamed into: the posted
    receive buffer (or its device staging buffer) when a match existed at
    header time -- zero intermediate copy for host receives -- otherwise a
    spill ``bytearray`` (the analogue of UCX's unexpected queue).  Complete
    in-process device messages skip sinks entirely: the array reference is
    held in ``device_payload``.
    """

    __slots__ = ("tag", "length", "sink", "received", "posted", "complete",
                 "discard", "spill", "device_payload", "remote", "progress",
                 "fc_owner", "fc_gen", "fc_bytes", "born")

    def __init__(self, tag: int, length: int):
        self.tag = tag
        self.length = length
        self.born = time.perf_counter()  # swpulse stall-unexp age origin (§25)
        self.sink: Optional[memoryview] = None
        self.received = 0
        self.posted: Optional[PostedRecv] = None
        self.complete = False
        self.discard = False
        self.spill: Optional[bytearray] = None
        self.device_payload = None
        # Flow-control debt (DESIGN.md §18): a message spilled into the
        # unexpected queue on a TCP conn carries its origin conn +
        # incarnation generation + payload bytes, so the matcher can
        # return the window grant the moment the memory is released
        # (fc_release).  Zero/None on every other path.
        self.fc_owner = None
        self.fc_gen = 0
        self.fc_bytes = 0
        # Remote-pull handle (device.py RemoteMsg): the payload lives on the
        # sender's transfer server until pulled.  Duck-typed: the matcher
        # only ever calls ``remote.start(msg)`` via fire thunks.
        self.remote = None
        # Optional RX progress hook (device sinks streaming directly into
        # their staging buffer): the conn calls ``progress(received)`` after
        # each read so placement can overlap the remaining stream
        # (device.py DeviceRecvSink.staged).  Duck-typed; None for host
        # targets and spill buffers.
        self.progress = None


def _copy_complete(pr: PostedRecv, payload, length: int) -> None:
    """Move a fully-arrived payload into a posted receive target."""
    if _is_host(pr.buf):
        if _is_host(payload):
            pr.buf[:length] = payload
        else:  # device payload -> host buffer
            pr.buf[:length] = payload.as_host_view()
    else:
        if _is_host(payload):
            direct = getattr(pr.buf, "accept_host", None)
            if direct is not None:
                # Complete bytes in hand: the sink places them directly,
                # skipping the staging bounce where the target platform
                # allows (see DeviceRecvSink.accept_host).  Streamed
                # arrivals still use host_staging.
                direct(payload, length)
            else:
                staging = pr.buf.host_staging()
                staging[:length] = payload
                pr.buf.finalize_from_host(length)
        else:  # device -> device: direct HBM handoff / ICI copy
            pr.buf.accept_device(payload.array)


class TagMatcher:
    """Worker-level matcher: FIFO posted-receive list + FIFO unexpected queue."""

    def __init__(self) -> None:
        self.posted: deque[PostedRecv] = deque()
        self.unexpected: deque[InboundMsg] = deque()
        # Messages whose payload is still streaming in (for close-time cancel).
        self.inflight: set[InboundMsg] = set()
        # swtrace observability (DESIGN.md §13): the owning Worker swaps in
        # its own Counters (and, when tracing is on, its TraceRing) so
        # match/completion accounting lands per worker.  Ring appends are
        # GIL-atomic data writes -- unlike user callbacks, they are safe
        # under the worker lock the matcher runs beneath.
        self.counters = swtrace.Counters()
        self.hists = swtrace.Hists()  # swapped for the Worker's (§25)
        self.trace = None
        # Flow control (DESIGN.md §18): total payload bytes currently
        # held by unexpected spill buffers (the STARWAY_UNEXP_BYTES cap
        # surface), and the worker-installed grant hook -- called UNDER
        # the worker lock (it only enqueues an engine op, never runs
        # user code or touches conn I/O).
        self.unexp_bytes = 0
        self.fc_grant = None  # fn(conn, gen, nbytes) | None

    # ------------------------------------------------------- flow control
    def fc_track(self, msg: "InboundMsg", conn, gen: int, nbytes: int) -> None:
        """Charge a spilled unexpected message against its origin conn's
        window accounting.  Caller holds the worker lock."""
        msg.fc_owner = conn
        msg.fc_gen = gen
        msg.fc_bytes = nbytes
        self.unexp_bytes += nbytes

    def fc_release(self, msg: "InboundMsg") -> None:
        """The spilled message's bytes left the unexpected queue (matched,
        truncated-dropped, purged): return the grant.  Idempotent; caller
        holds the worker lock."""
        n = msg.fc_bytes
        if not n:
            return
        msg.fc_bytes = 0
        self.unexp_bytes -= n
        if self.unexp_bytes < 0:
            self.unexp_bytes = 0
        owner, msg.fc_owner = msg.fc_owner, None
        if self.fc_grant is not None and owner is not None:
            self.fc_grant(owner, msg.fc_gen, n)

    def _rec_match(self, tag: int, length: int) -> None:
        tr = self.trace
        if tr is not None:
            tr.rec(swtrace.EV_RECV_MATCH, tag, 0, length)

    def _pulse_wait(self, pr: PostedRecv) -> None:
        # swpulse (§25): post -> delivery latency of a completed receive.
        us = int((time.perf_counter() - pr.t_post) * 1e6)
        self.hists.recv_wait_us[swtrace.hist_bucket(us)] += 1

    # ------------------------------------------------------------------ post
    def post_recv(self, buf, tag: int, mask: int, done: DoneCb, fail: FailCb, owner=None) -> list:
        """Post a receive.  Returns fire thunks (may complete immediately
        against a fully-arrived unexpected message)."""
        return self.post_recv_pr(PostedRecv(buf, tag, mask, done, fail, owner))

    def post_recv_pr(self, pr: PostedRecv) -> list:
        """:meth:`post_recv` with a caller-built record, so the caller can
        keep the handle (the deadline timer in core/engine.py cancels
        through it via :meth:`expire_recv`)."""
        buf, tag, mask, done, fail = pr.buf, pr.tag, pr.mask, pr.done, pr.fail
        fires: list = []
        size = _size(buf)
        for msg in self.unexpected:
            if msg.posted is None and not msg.discard and tags_match(msg.tag, tag, mask):
                if msg.length > size:
                    self.unexpected.remove(msg)
                    self.fc_release(msg)
                    fires.append(lambda fail=fail: fail(REASON_TRUNCATED))
                    if msg.remote is not None and not msg.complete:
                        # Unpulled remote payload: drain-pull it so the
                        # sender's buffer is released and flush barriers
                        # waiting on the descriptor can resolve.
                        msg.discard = True
                        fires.append(lambda m=msg: m.remote.start(m))
                    return fires
                if msg.remote is not None and not msg.complete:
                    # Unpulled remote payload: claim it and start the pull
                    # (outside the lock -- fires run after release).
                    pr.claimed = True
                    msg.posted = pr
                    self.unexpected.remove(msg)
                    self.inflight.add(msg)
                    self._rec_match(msg.tag, msg.length)
                    fires.append(lambda m=msg: m.remote.start(m))
                    return fires
                if msg.complete:
                    self.unexpected.remove(msg)
                    self.fc_release(msg)
                    if msg.device_payload is not None:
                        _copy_complete(pr, msg.device_payload, msg.length)
                    else:
                        _copy_complete(pr, memoryview(msg.spill)[: msg.length] if msg.spill is not None else memoryview(b""), msg.length)
                    stag, length = msg.tag, msg.length
                    self._rec_match(stag, length)
                    self.counters.recvs_completed += 1
                    self._pulse_wait(pr)
                    fires.append(lambda done=done, stag=stag, length=length: done(stag, length))
                    return fires
                # In flight: claim it; payload keeps streaming into the spill
                # buffer and is copied on completion.
                pr.claimed = True
                msg.posted = pr
                self._rec_match(msg.tag, msg.length)
                return fires
        self.posted.append(pr)
        return fires

    # -------------------------------------------------------- inbound (tcp)
    def on_message_start(self, tag: int, length: int) -> tuple[InboundMsg, list]:
        """Header of an inbound streamed message arrived.  Chooses the sink.

        Returns the message record plus fire thunks (a truncation failure
        fires immediately, like UCS_ERR_MESSAGE_TRUNCATED in the reference).
        """
        fires: list = []
        msg = InboundMsg(tag, length)
        if tag == PROBE_TAG:
            msg.discard = True  # bytes drain to scratch, nothing is queued
            return msg, fires
        self.inflight.add(msg)
        for pr in self.posted:
            if not pr.claimed and tags_match(tag, pr.tag, pr.mask):
                if length > pr.size:
                    # UCS_ERR_MESSAGE_TRUNCATED analogue: fail the receive now;
                    # the connection still consumes the payload (sink=None =>
                    # conn streams the bytes into its scratch discard buffer).
                    self.posted.remove(pr)
                    fires.append(lambda pr=pr: pr.fail(REASON_TRUNCATED))
                    msg.discard = True
                    return msg, fires
                pr.claimed = True
                msg.posted = pr
                self.posted.remove(pr)
                self._rec_match(tag, length)
                if _is_host(pr.buf):
                    msg.sink = pr.buf
                else:
                    msg.sink = pr.buf.host_staging()
                    msg.progress = getattr(pr.buf, "staged", None)
                return msg, fires
        msg.spill = bytearray(length)
        msg.sink = memoryview(msg.spill)
        self.unexpected.append(msg)
        return msg, fires

    def on_message_complete(self, msg: InboundMsg) -> list:
        """All payload bytes of ``msg`` have been ingested."""
        fires: list = []
        msg.complete = True
        self.inflight.discard(msg)
        if msg.discard:
            return fires
        pr = msg.posted
        if pr is not None:
            if msg.spill is not None:
                # Claimed mid-flight while spilling: move spill -> target.
                _copy_complete(pr, memoryview(msg.spill)[: msg.length], msg.length)
                try:
                    self.unexpected.remove(msg)
                except ValueError:
                    pass
                self.fc_release(msg)
            elif not _is_host(pr.buf):
                # Streamed straight into the device sink's staging buffer.
                pr.buf.finalize_from_host(msg.length)
            self.counters.recvs_completed += 1
            self._pulse_wait(pr)
            fires.append(lambda pr=pr, m=msg: pr.done(m.tag, m.length))
        # else: stays in the unexpected queue until a matching recv is posted.
        return fires

    # ------------------------------------------------------- remote (pull)
    def on_remote_message(self, tag: int, length: int, remote) -> tuple[InboundMsg, list]:
        """A DEVPULL descriptor arrived: the payload stays on the sender's
        transfer server until pulled.  Matches like :meth:`on_message_start`
        but starts a pull (via fire thunk) instead of choosing a sink."""
        fires: list = []
        msg = InboundMsg(tag, length)
        msg.remote = remote
        if tag == PROBE_TAG:
            msg.discard = True  # engine drain-pulls it, result dropped
            return msg, fires
        for pr in self.posted:
            if not pr.claimed and tags_match(tag, pr.tag, pr.mask):
                if length > pr.size:
                    self.posted.remove(pr)
                    fires.append(lambda pr=pr: pr.fail(REASON_TRUNCATED))
                    msg.discard = True
                    return msg, fires
                pr.claimed = True
                msg.posted = pr
                self.posted.remove(pr)
                self.inflight.add(msg)
                self._rec_match(tag, length)
                fires.append(lambda m=msg: m.remote.start(m))
                return msg, fires
        self.unexpected.append(msg)
        return msg, fires

    def on_remote_complete(self, msg: InboundMsg, payload, error: Optional[str]) -> list:
        """The pull for ``msg`` resolved.  ``payload`` is a device-payload
        duck type (``.array`` / ``.nbytes`` / ``as_host_view``) on success.

        On failure a claimed receive stays pending -- the peer-death
        contract (the sender's server died mid-delivery); an unclaimed
        message is dropped."""
        fires: list = []
        self.inflight.discard(msg)
        if msg.discard:
            return fires
        if error is not None:
            msg.discard = True
            if msg.posted is None:
                try:
                    self.unexpected.remove(msg)
                except ValueError:
                    pass
            else:
                # The sender's transfer server died mid-delivery: re-arm the
                # claimed receive so it stays matchable and, at close, gets
                # the standard cancel sweep (never silently orphaned).
                pr = msg.posted
                msg.posted = None
                pr.claimed = False
                self.posted.append(pr)
            return fires
        msg.complete = True
        pr = msg.posted
        if pr is not None:
            _copy_complete(pr, payload, msg.length)
            self.counters.recvs_completed += 1
            self._pulse_wait(pr)
            fires.append(lambda pr=pr, m=msg: pr.done(m.tag, m.length))
        else:
            # Force-started by a flush barrier before any receive matched:
            # hold the pulled array; a later post_recv takes the normal
            # complete-device-payload path.
            msg.device_payload = payload
        return fires

    # ------------------------------------------------------ inproc delivery
    def deliver(self, tag: int, payload) -> list:
        """Deliver a complete message in one step (in-process fast path).

        ``payload`` is a host memoryview (single copy into the posted buffer)
        or a DevicePayload (direct array handoff -- the path ICI device
        transfers ride, no host serialization).
        """
        fires: list = []
        length = _size(payload)
        if tag == PROBE_TAG:
            return fires  # probe traffic is dropped, never queued
        for pr in self.posted:
            if not pr.claimed and tags_match(tag, pr.tag, pr.mask):
                self.posted.remove(pr)
                if length > pr.size:
                    fires.append(lambda pr=pr: pr.fail(REASON_TRUNCATED))
                    return fires
                _copy_complete(pr, payload, length)
                self._rec_match(tag, length)
                self.counters.recvs_completed += 1
                self._pulse_wait(pr)
                fires.append(lambda pr=pr, t=tag, n=length: pr.done(t, n))
                return fires
        msg = InboundMsg(tag, length)
        if _is_host(payload):
            msg.spill = bytearray(payload)
        else:
            # Keep the array reference; no host copy unless a host receive
            # eventually claims it.
            msg.device_payload = payload
        msg.complete = True
        self.unexpected.append(msg)
        return fires

    # -------------------------------------------------------- conn death
    def purge_inflight(self, msg: InboundMsg) -> None:
        """The connection streaming ``msg`` died mid-payload.

        An unclaimed partial must not sit in the unexpected queue where a
        future post_recv would claim it and hang, and must not shadow a
        complete message with the same tag from a live peer.  A partial
        already claimed by a posted receive stays claimed: that receive
        never completes, matching the reference's peer-death semantics
        (tests/test_basic.py:250-277).
        """
        if msg.complete:
            return
        msg.discard = True
        self.inflight.discard(msg)
        self.fc_release(msg)
        if msg.posted is None:
            try:
                self.unexpected.remove(msg)
            except ValueError:
                pass

    # ----------------------------------------------------------- deadlines
    def expire_recv(self, pr: PostedRecv) -> list:
        """A deadline expired on a posted receive: withdraw it and fail it
        with the stable ``"timed out"`` reason.

        No-op (empty list) when the receive already completed or failed.
        A receive claimed mid-stream reuses the :meth:`purge_inflight`
        discipline: the partial message is discarded (remaining payload
        bytes drain to the connection's scratch buffer, never into the
        caller's buffer), it can never re-enter matching, and the caller's
        buffer is immediately safe to repost.
        """
        fires: list = []
        try:
            self.posted.remove(pr)
        except ValueError:
            # Not queued: completed already, or claimed by an in-flight
            # message (streamed or remote-pull) that is still arriving.
            for msg in list(self.inflight):
                if msg.posted is pr and not msg.complete:
                    msg.posted = None
                    msg.sink = None  # remaining bytes drain to conn scratch
                    msg.progress = None
                    self.purge_inflight(msg)
                    break
            else:
                return fires
        fires.append(lambda pr=pr: pr.fail(REASON_TIMEOUT))
        return fires

    # ----------------------------------------------------- liveness expiry
    def fail_pending(self, reason: str) -> list:
        """Fail every pending posted receive (queued or claimed mid-stream)
        with ``reason``, leaving complete unexpected messages intact so
        already-delivered data can still satisfy future receives.  The
        peer-liveness sweep (core/engine.py) runs this when the last alive
        connection expires -- the keepalive-enabled replacement for "peer
        death leaves posted recvs pending"."""
        fires: list = []
        while self.posted:
            pr = self.posted.popleft()
            fires.append(lambda pr=pr, reason=reason: pr.fail(reason))
        for msg in list(self.inflight):
            if msg.posted is not None and not msg.complete:
                pr = msg.posted
                msg.posted = None
                msg.sink = None
                msg.progress = None
                self.purge_inflight(msg)
                fires.append(lambda pr=pr, reason=reason: pr.fail(reason))
        return fires

    # --------------------------------------------------------------- close
    def cancel_all(self) -> list:
        """Fail every pending posted receive with the cancel reason.

        Mirrors the reference's close-time ``ucp_request_cancel`` sweep
        (src/bindings/main.cpp:483-507); the reason string must contain
        "cancel" (tests/test_basic.py:638-663).
        """
        fires: list = []
        while self.posted:
            pr = self.posted.popleft()
            self.counters.ops_cancelled += 1
            fires.append(lambda pr=pr: pr.fail(REASON_CANCELLED))
        # In-flight claimed messages (streaming directly into a posted buffer
        # or claimed while spilling): their PostedRecv is no longer in
        # self.posted; fail them too.
        for msg in list(self.inflight):
            if msg.posted is not None and not msg.complete:
                pr = msg.posted
                msg.posted = None
                msg.discard = True
                self.counters.ops_cancelled += 1
                fires.append(lambda pr=pr: pr.fail(REASON_CANCELLED))
        self.inflight.clear()
        self.unexpected.clear()
        self.unexp_bytes = 0  # close wipes the queue; grants are moot
        return fires
