"""Host-side tag-matching engine.

The reference delegates tag matching to UCX: receives are posted on the
*worker* (any endpoint, fan-in) with a 64-bit tag + mask and matched against
incoming messages by the transport (``ucp_tag_recv_nbx`` with wildcard masks,
reference: src/bindings/main.cpp:404,1172; fan-in behaviour pinned by
tests/test_basic.py:526-554).  TPU interconnects have no tag matching, so the
matcher is a first-class component of the host runtime (SURVEY.md section 7,
"Hard parts").

Matching rule (UCX semantics): a posted receive ``(rtag, rmask)`` matches an
incoming message with tag ``stag`` iff ``(stag & rmask) == (rtag & rmask)``.
``rmask == 0`` is the wildcard used throughout the reference tests
(tests/test_basic.py:547).  Both posted receives and unexpected messages are
kept in FIFO order, matching UCX's ordering guarantees.

Threading: the matcher is owned by a Worker and guarded by the worker's lock.
All mutating methods return a list of zero-argument "fire" thunks (completed /
failed user callbacks); the caller must invoke them *after* releasing the
worker lock so user callbacks can re-enter the API without deadlocking.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

from ..errors import REASON_CANCELLED, REASON_TRUNCATED

DoneCb = Callable[[int, int], None]  # (sender_tag, length)
FailCb = Callable[[str], None]


def tags_match(stag: int, rtag: int, rmask: int) -> bool:
    return (stag & rmask) == (rtag & rmask)


class PostedRecv:
    """A receive posted by the application, waiting for a matching message."""

    __slots__ = ("buf", "tag", "mask", "done", "fail", "claimed", "owner")

    def __init__(self, buf: memoryview, tag: int, mask: int, done: DoneCb, fail: FailCb, owner=None):
        self.buf = buf
        self.tag = tag
        self.mask = mask
        self.done = done
        self.fail = fail
        self.claimed = False  # an in-flight inbound message is streaming to us
        self.owner = owner  # keepalive for the python object owning buf


class InboundMsg:
    """An inbound message whose header has arrived.

    ``sink`` is where payload bytes are streamed: directly into the posted
    receive buffer when a match existed at header time (zero intermediate
    copy), otherwise into a spill ``bytearray`` (the unexpected-message queue,
    the analogue of UCX's unexpected queue).
    """

    __slots__ = ("tag", "length", "sink", "received", "posted", "complete", "discard", "spill")

    def __init__(self, tag: int, length: int):
        self.tag = tag
        self.length = length
        self.sink: Optional[memoryview] = None
        self.received = 0
        self.posted: Optional[PostedRecv] = None
        self.complete = False
        self.discard = False
        self.spill: Optional[bytearray] = None


class TagMatcher:
    """Worker-level matcher: FIFO posted-receive list + FIFO unexpected queue."""

    def __init__(self) -> None:
        self.posted: deque[PostedRecv] = deque()
        self.unexpected: deque[InboundMsg] = deque()
        # Messages whose payload is still streaming in (for close-time cancel).
        self.inflight: set[InboundMsg] = set()

    # ------------------------------------------------------------------ post
    def post_recv(self, buf: memoryview, tag: int, mask: int, done: DoneCb, fail: FailCb, owner=None) -> list:
        """Post a receive.  Returns fire thunks (may complete immediately
        against a fully-arrived unexpected message)."""
        fires: list = []
        for msg in self.unexpected:
            if msg.posted is None and not msg.discard and tags_match(msg.tag, tag, mask):
                if msg.length > len(buf):
                    self.unexpected.remove(msg)
                    fires.append(lambda fail=fail: fail(REASON_TRUNCATED))
                    return fires
                if msg.complete:
                    self.unexpected.remove(msg)
                    buf[: msg.length] = memoryview(msg.spill)[: msg.length] if msg.spill is not None else b""
                    stag, length = msg.tag, msg.length
                    fires.append(lambda done=done, stag=stag, length=length: done(stag, length))
                    return fires
                # In flight: claim it; payload keeps streaming into the spill
                # buffer and is copied on completion.
                pr = PostedRecv(buf, tag, mask, done, fail, owner)
                pr.claimed = True
                msg.posted = pr
                return fires
        self.posted.append(PostedRecv(buf, tag, mask, done, fail, owner))
        return fires

    # -------------------------------------------------------- inbound (tcp)
    def on_message_start(self, tag: int, length: int) -> tuple[InboundMsg, list]:
        """Header of an inbound message arrived.  Chooses the sink.

        Returns the message record plus fire thunks (a truncation failure
        fires immediately, like UCS_ERR_MESSAGE_TRUNCATED in the reference).
        """
        fires: list = []
        msg = InboundMsg(tag, length)
        self.inflight.add(msg)
        for pr in self.posted:
            if not pr.claimed and tags_match(tag, pr.tag, pr.mask):
                if length > len(pr.buf):
                    # UCS_ERR_MESSAGE_TRUNCATED analogue: fail the receive now;
                    # the connection still consumes the payload (sink=None =>
                    # conn streams the bytes into its scratch discard buffer).
                    self.posted.remove(pr)
                    fires.append(lambda pr=pr: pr.fail(REASON_TRUNCATED))
                    msg.discard = True
                    return msg, fires
                pr.claimed = True
                msg.posted = pr
                self.posted.remove(pr)
                msg.sink = pr.buf
                return msg, fires
        msg.spill = bytearray(length)
        msg.sink = memoryview(msg.spill)
        self.unexpected.append(msg)
        return msg, fires

    def on_message_complete(self, msg: InboundMsg) -> list:
        """All payload bytes of ``msg`` have been ingested."""
        fires: list = []
        msg.complete = True
        self.inflight.discard(msg)
        if msg.discard:
            return fires
        pr = msg.posted
        if pr is not None:
            if msg.spill is not None:
                # Claimed mid-flight while spilling: copy spill -> user buffer.
                pr.buf[: msg.length] = memoryview(msg.spill)[: msg.length]
                try:
                    self.unexpected.remove(msg)
                except ValueError:
                    pass
            fires.append(lambda pr=pr, m=msg: pr.done(m.tag, m.length))
        # else: stays in the unexpected queue until a matching recv is posted.
        return fires

    # ------------------------------------------------------ inproc delivery
    def deliver(self, tag: int, payload: memoryview) -> list:
        """Deliver a complete message in one step (in-process fast path).

        This is the path device-buffer transfers ride on: a single copy from
        the sender's buffer into the posted receive buffer, no serialization.
        """
        fires: list = []
        length = len(payload)
        for pr in self.posted:
            if not pr.claimed and tags_match(tag, pr.tag, pr.mask):
                self.posted.remove(pr)
                if length > len(pr.buf):
                    fires.append(lambda pr=pr: pr.fail(REASON_TRUNCATED))
                    return fires
                pr.buf[:length] = payload
                fires.append(lambda pr=pr, t=tag, n=length: pr.done(t, n))
                return fires
        msg = InboundMsg(tag, length)
        msg.spill = bytearray(payload)
        msg.complete = True
        self.unexpected.append(msg)
        return fires

    # --------------------------------------------------------------- close
    def cancel_all(self) -> list:
        """Fail every pending posted receive with the cancel reason.

        Mirrors the reference's close-time ``ucp_request_cancel`` sweep
        (src/bindings/main.cpp:483-507); the reason string must contain
        "cancel" (tests/test_basic.py:638-663).
        """
        fires: list = []
        while self.posted:
            pr = self.posted.popleft()
            fires.append(lambda pr=pr: pr.fail(REASON_CANCELLED))
        # In-flight claimed messages (streaming directly into a posted buffer
        # or claimed while spilling): their PostedRecv is no longer in
        # self.posted; fail them too.
        for msg in list(self.inflight):
            if msg.posted is not None and not msg.complete:
                pr = msg.posted
                msg.posted = None
                msg.discard = True
                fires.append(lambda pr=pr: pr.fail(REASON_CANCELLED))
        self.inflight.clear()
        self.unexpected.clear()
        return fires
