"""Wire protocol for the host (TCP / DCN-bootstrap) transport.

The reference gets its wire protocol for free from UCX tag matching
(``ucp_tag_send_nbx`` / ``ucp_tag_recv_nbx``, reference: src/bindings/main.cpp:370,404).
The TPU build has no tag-matching NIC, so the host transport speaks a small
framed protocol over a stream socket and the tag matcher lives in the worker
runtime (see core/matching.py).

Every frame starts with a fixed 17-byte little-endian header::

    u8  type
    u64 a
    u64 b

Frame types (fields a / b):

========= ============================ ======================================
type      a                            b
========= ============================ ======================================
HELLO     0                            length of JSON body that follows
HELLO_ACK 0                            length of JSON body that follows
DATA      sender tag                   payload length (bytes that follow)
FLUSH     flush sequence number        0
FLUSH_ACK flush sequence number        0
DEVPULL   sender tag                   length of JSON descriptor that follows
PING      sender tx time (ns; 0=none)  0
PONG      echoed PING tx time          responder tx time (ns)
SEQ       next session frame's seq     0
ACK       cumulative received seq      0
BYE       0                            0
SDATA     sender tag                   24-byte stripe sub-header + chunk
SACK      striped message id           echoed message total (bytes)
CREDIT    granted window bytes         0
RTS       sender tag                   length of JSON descriptor that follows
CTS       echoed rendezvous msg id     0
CSUM      next frame's full CRC32C     next frame's header(+sub) CRC32C
SNACK     corrupt chunk's msg id       corrupt chunk's offset (retransmit)
========= ============================ ======================================

PING / PONG are the *negotiated* peer-liveness probe (``"ka": "ok"``
offered in HELLO and confirmed in HELLO_ACK, like ``sm``/``devpull``):
when ``STARWAY_KEEPALIVE`` enables liveness detection (config.py), each
engine PINGs peers that have been silent for an interval and the peer
answers PONG; any inbound bytes count as proof of life.  A peer silent
for ``STARWAY_KEEPALIVE_MISSES`` intervals is declared dead.  Both
engines ignore unknown HELLO keys, so an old peer simply never confirms
``ka`` and is never PINGed -- all pairings interoperate.  On sm-upgraded
conns the probes ride the rings while the socket stays the doorbell +
liveness channel (core/shmring.py), so process death is still detected
instantly by EOF/RST and the PING path only covers silent wedges.

The probe pair doubles as the swscope clock-offset channel (DESIGN.md
§15): a PING may carry the sender's CLOCK_MONOTONIC timestamp in ``a``
(nanoseconds; 0 = plain liveness probe) and the PONG echoes it in ``a``
with the responder's own timestamp in ``b``.  The pinger then has an
NTP-style sample -- ``offset = t_responder - (t_tx + rtt/2)`` with error
``rtt/2`` -- recorded as an EV_CLOCK trace event so ``python -m
starway_tpu.trace --merge`` can align rings from different processes
onto one timeline.  Old peers answer a timestamped PING with a zero
PONG (no sample, never an error), so all pairings interoperate.  When
tracing is armed the connector additionally sends one timestamped PING
right after the handshake, so clock samples exist even with keepalive
off.

``tr`` is the swscope end-to-end trace negotiation: a connector with
tracing armed (STARWAY_TRACE / STARWAY_FLIGHT_DIR) offers ``"tr":
"<16-hex trace-conn id>"`` in HELLO; an acceptor that is also tracing
confirms with ``"tr": "ok"`` and both sides adopt the id.  Each engine
then emits an EV_E2E trace event per DATA/DEVPULL frame -- tagged with
the trace-conn id, the direction, and a per-conn per-direction wire
ordinal (delivery is in-order per conn, so equal ordinals at the two
ends are the same message; no per-frame wire bytes needed).  Session
replays never double-count: the sender records an ordinal once at the
frame's first full transmission (a replayed already-counted frame emits
a ``:sup`` superseded marker instead), and the receiver's seq dedup
drops duplicate frames before they reach the ordinal counter.

DEVPULL is a *negotiated extension* (``"devpull": "ok"`` offered in HELLO
and confirmed in HELLO_ACK, like ``sm``): instead of streaming a device
payload's bytes, the sender registers the array with its PJRT transfer
server (``jax.experimental.transfer``) and sends this small descriptor
``{"u": uuid, "a": server_address, "n": nbytes, "s": shape, "d": dtype}``;
the receiver pulls the buffer device-to-device over the PJRT socket --
no host staging in the framework.  Both engines speak it: the Python
engine natively, the C++ engine by surfacing descriptors to its wrapper
(sw_engine.h "devpull").  A process that cannot pull (no jax, or backend
not up at handshake time) never negotiates the capability and peers fall
back to staged DATA frames, so all pairings interoperate (see device.py
TransferManager; the flush barrier covers pulls because the receiver
defers FLUSH_ACK until descriptors received before the FLUSH have
resolved).

HELLO is sent by the connector and carries ``{"worker_id", "mode", "name"}``
-- the analogue of the reference's worker-address Active-Message handshake
(AM id 0x7A, reference: src/bindings/main.cpp:25,292-334).  ``mode`` is
``"socket"`` or ``"address"``; in address mode the accepted endpoint reports
empty socket fields, mirroring the reference (README.md:141-143).

HELLO may additionally offer a same-host shared-memory upgrade
(``sm_key`` / ``sm_nonce`` / ``sm_ring`` -- see core/shmring.py); an
acceptor that successfully maps the segment confirms with ``"sm": "ok"``
in HELLO_ACK and both sides move the framed stream onto the rings, keeping
the socket as doorbell + liveness channel.  All extra values are JSON
strings so the native engine's minimal extractor can read them, and both
engines ignore unknown keys -- old and new peers interoperate, falling
back to plain TCP.  This mirrors UCX's transport negotiation
(``UCX_TLS`` including ``sm``; reference: benchmark.md:114-126).

SEQ / ACK belong to the *negotiated* resilient-session layer
(``STARWAY_SESSION``, offered as ``"sess": "ok"`` with a stable
``sess_id`` / ``sess_epoch`` / ``sess_ack`` triple in HELLO and confirmed
in HELLO_ACK -- all JSON strings, like the other extensions): on a
session conn every replayable frame (DATA / DEVPULL / FLUSH / FLUSH_ACK)
is preceded by a SEQ frame announcing its per-conn sequence number.  The
receiver tracks the cumulative in-order seq, drops any frame whose seq it
has already processed (exactly-once delivery across replays), and sends
cumulative ACKs -- piggybacked on each read pass and flushed by an idle
timer.  The sender keeps unacked frames in a bounded replay journal and,
after a reconnect handshake carrying the same ``sess_id``/``sess_epoch``,
replays everything past the peer's ``sess_ack``.  PING/PONG/ACK/handshake
frames are per-connection-incarnation and are never sequenced or
journaled.  BYE is the session goodbye: a peer closing *locally* on a
clean frame boundary sends it (best-effort) right before the FIN so the
survivor knows the session is over and takes the seed/keepalive death
contract immediately -- without it, EOF is indistinguishable from a
crash and the survivor would suspend for the full grace window.  A lost
BYE only costs the peer that grace-expiry fallback.  See DESIGN.md §14.

SDATA / SACK are the *negotiated* multi-rail striping plane (DESIGN.md
§17).  A connector started with ``STARWAY_RAILS=N`` offers
``"rails": "<N>"`` in the primary HELLO; a striping-capable acceptor
confirms ``"rails": "ok"`` and the connector dials N-1 extra TCP conns
whose HELLO carries ``"rail_of": "<primary worker_id>"`` -- the acceptor
attaches each to the existing endpoint (confirming ``"rail": "ok"``)
instead of creating a new one.  A send at or above
``STARWAY_STRIPE_THRESHOLD`` on a railed conn is then split at
``STARWAY_STRIPE_CHUNK`` granularity and each chunk travels as one SDATA
frame on whichever rail claims it first (completion-driven work
stealing): header ``a`` = sender tag, ``b`` = body length, and the body
opens with the 24-byte little-endian sub-header ``u64 msg_id, u64
offset, u64 total`` followed by the chunk bytes.  The receiver
reassembles by offset into one matcher message keyed by (rail group,
msg_id), drops duplicate offsets (chunks are idempotent, which is what
makes rail-death redistribution and session replay exactly-once), and
answers SACK (``a`` = msg_id, ``b`` = total) when the last byte lands --
the sender's signal to release the pinned payload.  Old peers never
negotiate ``rails`` and never see either frame; sub-threshold sends ride
ordinary DATA frames on the primary rail even when striping is on.

CREDIT / RTS / CTS are the *negotiated* receiver-driven flow-control
plane (DESIGN.md §18).  A peer started with ``STARWAY_FC_WINDOW=N``
offers ``"fc": "<N>"`` in HELLO -- "my unexpected-queue budget for your
eager traffic is N bytes"; an fc-capable acceptor confirms with its own
``"fc": "<M>"`` in HELLO_ACK and each direction is then governed by the
RECEIVER's advertised window.  The sender debits the window per eager
DATA payload and parks sends unframed-FIFO when it runs dry (one
oversized frame is admitted against an idle window so nothing
deadlocks); the receiver returns CREDIT grants (``a`` = bytes) as the
debited messages are matched into posted receives or drained, which is
what bounds receiver unexpected-queue memory to the window.  Sends
above the rendezvous threshold never consume window: the sender
announces them with a small RTS descriptor (``a`` = tag, JSON body
``{"m": msg_id, "n": total}`` -- the devpull-descriptor shape, and the
receiver queues it through the same matcher machinery), the receiver
answers CTS (``a`` = msg_id) once a matching receive claims it (or a
flush barrier forces it), and the payload then travels as one
self-describing T_SDATA frame routed into the pre-registered assembly
and SACKed at the last byte -- the sender pins the payload until that
SACK, so a session resume can safely re-announce it.  Old peers never
confirm ``fc`` and see none of the three frames; with the env unset the
HELLO is byte-identical to the seed.

CSUM / SNACK are the *negotiated* end-to-end integrity plane
(``STARWAY_INTEGRITY``, DESIGN.md §19).  A peer started with the knob
offers ``"csum": "1"`` in HELLO; an integrity-capable acceptor confirms
``"csum": "ok"`` and every subsequent framed message on the conn -- DATA,
ctl, striped chunks, everything except the handshake pair and the T_SEQ
session prefix -- is preceded by one T_CSUM frame: ``a`` is the CRC32C
(Castagnoli; :func:`crc32c`) over the next frame's entire header+body
bytes, ``b`` the CRC32C over just its header (plus the 24-byte sub-header
for T_SDATA).  The receiver verifies ``b`` the moment the routing fields
are parsed -- BEFORE the payload streams into a user buffer -- so a
corrupted length/offset can never desync the stream or scribble on a
verified region; ``a`` is verified at the frame's last byte.  The two
recovery paths: a corrupt striped T_SDATA chunk with an intact sub-header
answers T_SNACK (``a`` = msg id, ``b`` = chunk offset) and the sender
re-queues JUST that chunk through the §17 offset-dedup reassembly
(payloads are pinned until T_SACK, so the resend is always legal); any
other mismatch poisons the conn with the stable ``"corrupt"`` reason --
seed contract without sessions, suspend+replay with them.  Wrap order on
session conns is ``[T_SEQ][T_CSUM][frame]``: the checksum rides inside
the sequenced envelope and replays byte-identically from the journal.
Old peers never confirm ``csum`` and see neither frame; with the env
unset the HELLO is byte-identical to the seed.

FLUSH / FLUSH_ACK implement the delivery barrier: because the byte stream is
processed in order, a FLUSH_ACK for sequence *n* proves every DATA payload
enqueued before flush *n* has been fully ingested by the peer's matching
engine -- the semantics ``ucp_worker_flush_nbx`` provides in the reference
(src/bindings/main.cpp:432,1202; behaviour pinned by tests/test_basic.py:190-415).
"""

from __future__ import annotations

import ctypes
import json
import struct

HEADER = struct.Struct("<BQQ")
HEADER_SIZE = HEADER.size  # 17

T_HELLO = 1
T_HELLO_ACK = 2
T_DATA = 3
T_FLUSH = 4
T_FLUSH_ACK = 5
T_DEVPULL = 6
T_PING = 7
T_PONG = 8
T_SEQ = 9
T_ACK = 10
T_BYE = 11
T_SDATA = 12
T_SACK = 13
T_CREDIT = 14
T_RTS = 15
T_CTS = 16
T_CSUM = 17
T_SNACK = 18

# Canonical frame-name table for the swrefine protocol-event channel
# (DESIGN.md §22): ``rx:<NAME>``/``tx:<NAME>`` events and the protocol
# monitor automaton use exactly these names -- the T_* suffix, which is
# also the protomodel annotation vocabulary (analysis/protomodel.py
# KNOWN_INPUTS).  Cross-engine contract surface: the C++ engine carries
# the same table as ``proto_frame_name()`` in sw_engine.cpp, and the
# `refine` analysis pass diffs the two entry-by-entry (a frame type
# missing from either table, or mapped to a different name, is a merge-
# gate finding).  Types absent from the table render as "OTHER" -- the
# unknown-frame dispatch arm.
FRAME_NAMES = {
    T_HELLO: "HELLO",
    T_HELLO_ACK: "HELLO_ACK",
    T_DATA: "DATA",
    T_FLUSH: "FLUSH",
    T_FLUSH_ACK: "FLUSH_ACK",
    T_DEVPULL: "DEVPULL",
    T_PING: "PING",
    T_PONG: "PONG",
    T_SEQ: "SEQ",
    T_ACK: "ACK",
    T_BYE: "BYE",
    T_SDATA: "SDATA",
    T_SACK: "SACK",
    T_CREDIT: "CREDIT",
    T_RTS: "RTS",
    T_CTS: "CTS",
    T_CSUM: "CSUM",
    T_SNACK: "SNACK",
}

# Rendezvous (RTS/CTS) message-id namespace bit (DESIGN.md §18): fc msg
# ids carry the top bit so they can never collide with stripe msg ids on
# a railed+fc conn -- both families share the receiver's assembly table
# and completed-id LRU.  Cross-engine contract (FC_MSG_BIT in
# sw_engine.cpp).
FC_MSG_BIT = 1 << 63

# §19 per-frame checksum scope (DESIGN.md §19, §21): the frame types
# exempt from the negotiated T_CSUM prefix (the handshake pair predates
# negotiation; the T_SEQ session prefix glues OUTSIDE the envelope --
# wire order [SEQ][CSUM][frame]), and the types whose bytes continue past
# the 17-byte header (their full-frame CRC verifies at the last payload
# byte; every other protected type is header-only and verifies at
# dispatch).  Shared by the live parser (core/conn.py), the reference
# decoder below, and -- as kCsumExempt[]/kCsumBody[] -- the C++ engine's
# parser and decode harness; membership is cross-engine contract surface
# diffed by the `wirefuzz` analysis pass.
CSUM_EXEMPT = frozenset((T_HELLO, T_HELLO_ACK, T_SEQ))
CSUM_BODY = frozenset((T_DATA, T_DEVPULL, T_RTS))

# Upper bound on a control-frame JSON body (DESIGN.md §21).  The
# HELLO/HELLO_ACK/DEVPULL/RTS descriptors are tiny, but the engines
# allocate/accumulate `b` bytes for them, so an unchecked length field is
# a remote allocation primitive -- and a zero length is degenerate (the
# Python parser used to issue a 0-byte read that a TCP socket reports as
# EOF and an sm ring reports as idle: conn death on one transport, a
# silent permanent stall on the other, while the C++ engine silently
# dropped the frame).  A ctl frame announcing b == 0 or b > CTL_MAX is a
# protocol violation and breaks the conn in BOTH engines (CTL_MAX
# constexpr in sw_engine.cpp; wirefuzz corpus seeds pin both edges).
CTL_MAX = 1 << 20

#: Frame types that are exactly one 17-byte header on the wire.  T_CSUM
#: is deliberately absent: on a conn that never negotiated "csum" it is
#: an unknown frame (conn death), and on an integrity conn it is the
#: envelope the verification gate consumes before dispatch.
HEADER_ONLY = frozenset((T_FLUSH, T_FLUSH_ACK, T_PING, T_PONG, T_SEQ,
                         T_ACK, T_BYE, T_SACK, T_CREDIT, T_CTS, T_SNACK))

# Striped-DATA sub-header (DESIGN.md §17): u64 msg_id, u64 offset,
# u64 total -- little-endian, leading every SDATA body.  The 24-byte size
# is cross-engine contract surface (SDATA_SUB_SIZE in sw_engine.cpp,
# machine-checked by `python -m starway_tpu.analysis`).
SDATA_SUB = struct.Struct("<QQQ")
SDATA_SUB_SIZE = SDATA_SUB.size  # 24


# ------------------------------------------------------------- integrity
#
# CRC32C (Castagnoli, the iSCSI/ext4 polynomial) is the integrity plane's
# checksum (DESIGN.md §19): the native engine computes it with the SSE4.2
# / ARMv8 CRC instructions (sw_crc32c, software slicing fallback), and
# the Python engine calls that same export through ctypes so both engines
# -- and both ends of a mixed pair -- agree bit-for-bit.  The pure-Python
# table below is the no-toolchain fallback; tests pin it against the
# native export and the standard check vector crc32c(b"123456789") ==
# 0xE3069283.  The chaining contract matches zlib.crc32: ``crc`` is the
# previous call's RESULT (the implementation re-inverts internally), so
# a payload can be folded incrementally chunk by chunk.

_CRC32C_POLY = 0x82F63B78
_crc_table: list | None = None
_crc_native = None  # ctypes fn, or False once probed absent


def _crc32c_table() -> list:
    global _crc_table
    if _crc_table is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            tbl.append(c)
        _crc_table = tbl
    return _crc_table


def _crc32c_fn():
    """The native sw_crc32c export, or False.  Probed lazily and only
    against an already-built artifact -- the first checksum computes on
    the connection path, where a synchronous g++ build would stall it
    (the shmring.atomics(build=False) discipline)."""
    global _crc_native
    if _crc_native is None:
        try:
            from . import native

            fn = native.crc32c_fn(build=False)
        except Exception:
            fn = None
        _crc_native = fn if fn is not None else False
    return _crc_native


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of ``data`` chained onto a previous result ``crc`` (zlib
    calling convention).  Accepts any C-contiguous buffer."""
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    n = len(mv)
    if n == 0:
        return crc & 0xFFFFFFFF
    fn = _crc32c_fn()
    if fn is not False:
        try:
            buf = (ctypes.c_ubyte * n).from_buffer(mv)
        except TypeError:
            # Read-only source.  A whole immutable buffer (bytes payloads,
            # packed ctl frames) crosses ctypes as a borrowed pointer --
            # no copy; only a read-only *slice* (rare, small spans) pays a
            # materialisation.
            base = getattr(mv, "obj", None)
            if isinstance(base, bytes) and len(base) == n:
                buf = base
            else:
                buf = bytes(mv)
        return fn(buf, n, crc & 0xFFFFFFFF)
    tbl = _crc32c_table()
    c = (crc & 0xFFFFFFFF) ^ 0xFFFFFFFF
    for b in bytes(mv):
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def pack_header(ftype: int, a: int, b: int) -> bytes:
    return HEADER.pack(ftype, a, b)


def unpack_header(buf) -> tuple[int, int, int]:
    return HEADER.unpack(buf)


def pack_hello(worker_id: str, mode: str, name: str = "", extra: dict | None = None) -> bytes:
    fields = {"worker_id": worker_id, "mode": mode, "name": name}
    if extra:
        fields.update(extra)
    body = json.dumps(fields, separators=(",", ":")).encode()
    return pack_header(T_HELLO, 0, len(body)) + body


def pack_hello_ack(worker_id: str, extra: dict | None = None) -> bytes:
    fields = {"worker_id": worker_id}
    if extra:
        fields.update(extra)
    body = json.dumps(fields, separators=(",", ":")).encode()
    return pack_header(T_HELLO_ACK, 0, len(body)) + body


def unpack_json_body(body) -> dict:
    """Parse a JSON control body straight from the accumulation buffer.

    ``json.loads`` reads ``bytes``/``bytearray`` directly, so the callers
    (core/conn.py's ctl parser, core/engine.py's handshake) pass their
    buffers as-is with no intermediate full-body ``bytes()`` copy; a
    ``memoryview`` is materialised here because json cannot read one."""
    if isinstance(body, memoryview):
        body = body.tobytes()
    try:
        info = json.loads(body)
    except RecursionError:
        # A nesting bomb (b"["*50000 fits well under CTL_MAX) must be a
        # protocol violation like any other malformed body, never an
        # engine-thread escape that emergency-closes the whole worker.
        raise ValueError("ctl body nesting too deep") from None
    if not isinstance(info, dict):
        # Every ctl body in the protocol is a JSON OBJECT; valid JSON of
        # the wrong shape ([] / "x" / 42) would otherwise raise from the
        # handlers' .get() field access off the event loop.  The native
        # engine enforces the same object shape at its ctl dispatch.
        raise ValueError("ctl body is not a JSON object")
    return info


def pack_data_header(tag: int, length: int) -> bytes:
    return pack_header(T_DATA, tag, length)


def pack_flush(seq: int) -> bytes:
    return pack_header(T_FLUSH, seq, 0)


def pack_flush_ack(seq: int) -> bytes:
    return pack_header(T_FLUSH_ACK, seq, 0)


def pack_ping(t_ns: int = 0) -> bytes:
    """Liveness probe; ``t_ns`` (CLOCK_MONOTONIC nanoseconds) arms the
    swscope clock-sample reply, 0 keeps the plain PR-1 probe."""
    return pack_header(T_PING, t_ns, 0)


def pack_pong(echo_ns: int = 0, t_ns: int = 0) -> bytes:
    """Probe answer: echo the PING's timestamp and stamp our own."""
    return pack_header(T_PONG, echo_ns, t_ns)


def pack_seq(seq: int) -> bytes:
    return pack_header(T_SEQ, seq, 0)


def pack_ack(cum_seq: int) -> bytes:
    return pack_header(T_ACK, cum_seq, 0)


def pack_bye() -> bytes:
    return pack_header(T_BYE, 0, 0)


def pack_sdata_header(tag: int, msg_id: int, offset: int, total: int,
                      chunk_len: int) -> bytes:
    """Header + sub-header of one striped chunk (payload bytes follow)."""
    return (pack_header(T_SDATA, tag, SDATA_SUB_SIZE + chunk_len)
            + SDATA_SUB.pack(msg_id, offset, total))


def pack_sack(msg_id: int, total: int) -> bytes:
    return pack_header(T_SACK, msg_id, total)


def pack_credit(nbytes: int) -> bytes:
    """Receiver-driven window grant: ``nbytes`` of eager budget returned
    to the sender (DESIGN.md §18)."""
    return pack_header(T_CREDIT, nbytes, 0)


def pack_rts(tag: int, msg_id: int, total: int) -> bytes:
    """Rendezvous announcement: a tiny descriptor instead of the payload
    (the devpull-descriptor shape; the receiver pulls via CTS)."""
    body = json.dumps({"m": msg_id, "n": total},
                      separators=(",", ":")).encode()
    return pack_header(T_RTS, tag, len(body)) + body


def pack_cts(msg_id: int) -> bytes:
    return pack_header(T_CTS, msg_id, 0)


def pack_snack(msg_id: int, offset: int) -> bytes:
    """Chunk-level retransmit request (DESIGN.md §19): the T_SDATA chunk
    at ``offset`` of ``msg_id`` failed payload verification; its routing
    sub-header verified, so only that chunk needs to ride again."""
    return pack_header(T_SNACK, msg_id, offset)


def pack_csum_for(frame_bytes, payload=None) -> bytes:
    """The T_CSUM prefix for one outgoing frame (DESIGN.md §19).

    ``frame_bytes`` is everything of the frame already materialised as
    bytes (header, plus any sub-header/JSON body); ``payload`` the
    remaining flat payload view, if any.  ``b`` (crc_head) covers the
    17-byte header -- plus the 24-byte stripe sub-header for T_SDATA --
    so the receiver validates routing fields before streaming the
    payload; ``a`` (crc_frame) covers every byte of the frame."""
    head_len = HEADER_SIZE
    if frame_bytes[0] == T_SDATA:
        head_len += SDATA_SUB_SIZE
    if head_len > len(frame_bytes):
        head_len = len(frame_bytes)
    ch = crc32c(frame_bytes[:head_len])
    cf = ch
    if len(frame_bytes) > head_len:
        cf = crc32c(frame_bytes[head_len:], cf)
    if payload is not None and len(payload):
        cf = crc32c(payload, cf)
    return pack_header(T_CSUM, cf, ch)


def pack_devpull(tag: int, desc: dict) -> bytes:
    body = json.dumps(desc, separators=(",", ":")).encode()
    return pack_header(T_DEVPULL, tag, len(body)) + body


# ------------------------------------------------- reference decoder
#
# The normative structural decoder for the framed stream: the exact
# accept/reject/short outcome of core/conn.py's `_pump_frames` parser
# (and the C++ engine's `pump_frames`), as one pure function over a flat
# byte buffer.  `python -m starway_tpu.analysis` (the `wirefuzz` pass,
# DESIGN.md §21) feeds identical adversarial buffers to this function, to
# its own grammar-derived oracle, and to the native engine's
# `sw_wire_decode` export, and diffs the canonical outcome strings --
# any divergence is a cross-engine contract finding.  Keep this function
# in lockstep with `_pump_frames`: it IS the written-down decode
# contract ("two engines, one wire format", CLAUDE.md).

#: Cap on rendered frame entries (both engines truncate identically so
#: the canonical strings stay diffable on long streams).
DECODE_MAX_ENTRIES = 64


def fmt_decode(status: str, consumed: int, entries: list) -> str:
    """Canonical decode-outcome string: shared, byte-identical format
    between this module, analysis/wirefuzz.py's oracle, shmring's record
    decoder, and the native sw_wire_decode export."""
    shown = entries[:DECODE_MAX_ENTRIES]
    extra = len(entries) - len(shown)
    if extra > 0:
        shown.append(f"+{extra}")
    return f"{status} n={consumed} [" + " ".join(shown) + "]"


def decode_stream(data, csum: bool = False) -> str:
    """Decode one framed byte stream and return the canonical outcome.

    ``csum=True`` decodes under the negotiated §19 integrity plane (the
    T_CSUM envelope rules).  The outcome triple is (status, consumed,
    frames): status is ``ok`` (buffer ends on a frame boundary),
    ``short:<state>`` (mid-frame: more bytes would continue the stream),
    or ``reject(<reason>)`` (the engines poison/break the conn here --
    ``<reason>`` uses the engines' stable corruption phrases).
    ``n=<consumed>`` counts bytes of fully-processed frames; entries are
    ``type:a:b`` (T_SDATA adds ``:msg_id:off:total``; a corrupt striped
    chunk with verified routing renders the recoverable
    ``snack:msg_id:off`` event instead -- the §19 retransmit path, not a
    poison).  Never allocates from wire-controlled lengths."""
    buf = bytes(data)  # swcheck: allow(hotpath-copy): bounded fuzz/gate input, never a data path
    n = len(buf)
    pos = 0
    consumed = 0
    entries: list = []
    pend = None  # armed T_CSUM envelope: (crc_frame, crc_head)
    accum = 0

    def done(status: str) -> str:
        return fmt_decode(status, consumed, entries)

    while True:
        if pos + HEADER_SIZE > n:
            return done("ok" if pos == n else "short:header")
        hdr = buf[pos:pos + HEADER_SIZE]
        ftype, a, b = HEADER.unpack(hdr)
        if pend is not None:
            # The protected frame's header is covered too: a corrupted
            # length field must never desync the stream (§19).
            accum = crc32c(hdr, accum)
        pos += HEADER_SIZE
        if csum:
            # §19 verification gate, BEFORE dispatch (conn.py twin).
            if ftype == T_CSUM:
                if pend is not None:
                    return done("reject(nested checksum prefix)")
                # Engines keep only the low 32 bits (the CRC width).
                pend = (a & 0xFFFFFFFF, b & 0xFFFFFFFF)
                accum = 0
                entries.append(f"{ftype}:{a}:{b}")
                consumed = pos
                continue
            if ftype not in CSUM_EXEMPT:
                if pend is None:
                    return done("reject(frame without checksum)")
                if ftype != T_SDATA and accum != pend[1]:
                    return done("reject(frame header checksum)")
                body_follows = (ftype == T_SDATA
                                or (ftype in CSUM_BODY and b > 0))
                if not body_follows:
                    cf, pend = pend[0], None
                    if accum != cf:
                        return done("reject(frame checksum)")
        if ftype == T_SDATA:
            if b <= SDATA_SUB_SIZE:
                return done("reject(sdata sub-header)")
            if pos + SDATA_SUB_SIZE > n:
                return done("short:sub")
            sub = buf[pos:pos + SDATA_SUB_SIZE]
            if pend is not None:
                accum = crc32c(sub, accum)
                if accum != pend[1]:
                    return done("reject(stripe sub-header checksum)")
            msg_id, off, total = SDATA_SUB.unpack(sub)
            pos += SDATA_SUB_SIZE
            clen = b - SDATA_SUB_SIZE
            if pos + clen > n:
                return done("short:body")
            if pend is not None:
                accum = crc32c(buf[pos:pos + clen], accum)
                cf, pend = pend[0], None
                if accum != cf:
                    # Chunk payload corrupt, routing verified: the
                    # recoverable T_SNACK retransmit, conn stays healthy.
                    pos += clen
                    entries.append(f"snack:{msg_id}:{off}")
                    consumed = pos
                    continue
            pos += clen
            entries.append(f"{ftype}:{a}:{b}:{msg_id}:{off}:{total}")
            consumed = pos
            continue
        if ftype == T_DATA:
            if b:
                if pos + b > n:
                    return done("short:body")
                if pend is not None:
                    accum = crc32c(buf[pos:pos + b], accum)
                    cf, pend = pend[0], None
                    if accum != cf:
                        return done("reject(payload checksum (DATA))")
                pos += b
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        if ftype in (T_HELLO, T_HELLO_ACK, T_DEVPULL, T_RTS):
            if b == 0:
                return done("reject(zero control body)")
            if b > CTL_MAX:
                return done("reject(oversized control body)")
            if pos + b > n:
                return done("short:body")
            if pend is not None:
                # The ctl-completion verify consumes the envelope even
                # for the (nonsensical) exempt-frame-inside-envelope
                # shape -- both engines clear pend at any ctl body end.
                accum = crc32c(buf[pos:pos + b], accum)
                cf, pend = pend[0], None
                if accum != cf:
                    return done("reject(control body checksum)")
            pos += b
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        if ftype in HEADER_ONLY:
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        return done("reject(unknown frame type)")
