"""Multi-rail striping: the Lane abstraction and the stripe scheduler.

One message, many transports (DESIGN.md §17; ROADMAP item 1).  A railed
connection (``STARWAY_RAILS``, core/frames.py ``"rails"``/``"rail_of"``
handshake keys) exposes N interchangeable :class:`Lane` objects -- the
primary conn (tcp or sm-upgraded) plus N-1 secondary TCP conns -- and a
send at or above ``STARWAY_STRIPE_THRESHOLD`` is split at
``STARWAY_STRIPE_CHUNK`` granularity and pushed across ALL of them
concurrently:

* **TX** -- :class:`RailGroup` (on the primary conn) owns a FIFO of
  :class:`StripeSource` records (one per striped message, holding the
  payload by reference until the receiver's T_SACK).  Each lane runs one
  persistent :class:`StripeFeeder` tx item that *claims* the next chunk
  from the group the moment its current chunk finishes writing --
  completion-driven work stealing, not static round-robin: a lane twice
  as fast naturally carries twice the chunks, and a stalled lane stops
  claiming.  Each lane tracks an EWMA of its delivered throughput, and
  under ``STARWAY_STRIPE_WEIGHTED`` a slow lane (below half the fastest
  live lane's EWMA) declines *steal* claims in a message's tail so the
  last chunks avoid stragglers -- dispatch claims are never declined,
  which is what keeps a declined chunk from stranding (DESIGN.md §17).
  Each chunk travels as a self-describing T_SDATA frame
  (msg id, offset, total), so chunks are idempotent and unordered.
* **RX** -- :class:`StripeRx` (on the receiving side's primary conn)
  reassembles by offset into ONE matcher message per msg id, whatever
  rail each chunk arrived on.  Duplicate offsets are drained and dropped
  (exactly-once bytes under rail death, FaultProxy ``duplicate``, and
  session replay), and assembly completion answers T_SACK.
* **Failure** -- a *rail* dying mid-stripe re-queues that rail's
  claimed-but-unacked chunks onto the surviving lanes
  (``rail_resteals``); the payload is pinned until SACK, so the resend is
  always legal.  Only the PRIMARY dying takes the usual contract: seed
  semantics fail the striped ops, a live session suspends and
  re-dispatches every un-SACKed source wholesale at resume -- sessions
  journal per-message, never per-lane (CLAUDE.md invariant).

The flush barrier never rides the rails: secondary lanes carry only
SDATA/SACK (+ liveness probes), and a worker/endpoint flush additionally
waits until every source submitted before it is SACKed
(core/engine.py FlushRec.stripe_waits) -- which covers striped delivery
end-to-end even while chunks are mid-resteal.

The C++ engine implements the identical scheduler in
native/sw_engine.cpp (``StripeSrc``/``StripeAsm``); all four engine
pairings interoperate chunk-for-chunk.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from .. import config
from ..errors import REASON_CANCELLED
from . import frames, swtrace

#: EWMA smoothing for per-lane delivered throughput (one update per
#: completed chunk; ~3-4 chunks to converge after a speed change).
EWMA_ALPHA = 0.3

#: A lane slower than this fraction of the fastest live lane's EWMA
#: declines tail steals under STARWAY_STRIPE_WEIGHTED.
SLOW_FRACTION = 0.5

#: Completed-message ids remembered per receiving rail group so a late or
#: replayed chunk re-SACKs instead of corrupting state.  Bounded: the
#: sender stops resending a message at first SACK, so only a small recent
#: window can ever see stragglers.
DONE_LRU = 4096


class Lane:
    """Stripe-target view of one transport (a conn): the scheduling unit
    of the rail set.  ``idx`` 0 is the primary; the feeder is this lane's
    persistent tx item while it has (or may claim) chunks."""

    __slots__ = ("conn", "idx", "feeder", "chunks_tx", "ewma_bps",
                 "tail_declines")

    def __init__(self, conn, idx: int):
        self.conn = conn
        self.idx = idx
        self.feeder: Optional["StripeFeeder"] = None
        self.chunks_tx = 0  # cumulative chunks this lane carried (balance)
        self.ewma_bps = 0.0  # delivered-throughput EWMA (0 = no data yet)
        self.tail_declines = 0  # tail steals declined as the slow lane

    @property
    def alive(self) -> bool:
        c = self.conn
        return c.alive and c.sock is not None

    def note_chunk(self, nbytes: int, dt: float) -> None:
        """One chunk fully written after ``dt`` seconds on this lane:
        fold it into the throughput EWMA (tracked unconditionally -- one
        multiply per chunk; only the weighted-claim *policy* is gated)."""
        if dt <= 0.0 or nbytes <= 0:
            return
        bps = nbytes / dt
        self.ewma_bps = (bps if self.ewma_bps == 0.0
                         else (1.0 - EWMA_ALPHA) * self.ewma_bps
                         + EWMA_ALPHA * bps)


class StripeSource:
    """One striped outgoing message.  Holds the payload BY REFERENCE
    until the receiver's T_SACK (or terminal failure): chunks may be
    resent after a rail death or a session resume, so the bytes must stay
    stable -- rendezvous rules, whatever the size (config.py
    STARWAY_STRIPE_THRESHOLD)."""

    __slots__ = ("msg_id", "tag", "payload", "total", "chunk", "done",
                 "fail", "owner", "pending", "rail_offs", "done_offs",
                 "unwritten", "writers", "local_done", "counted", "sacked",
                 "failed", "t_post", "__weakref__")

    def __init__(self, msg_id: int, tag: int, payload, done, fail, owner,
                 chunk: int):
        self.t_post = time.perf_counter()  # swpulse pin/send origin (§25)
        self.msg_id = msg_id
        self.tag = tag
        self.payload = payload
        self.total = len(payload)
        self.chunk = chunk
        self.done = done
        self.fail = fail
        self.owner = owner
        self.pending: deque = deque(range(0, self.total, chunk))
        # Per-lane chunk ledgers, kept until SACK so a dead rail's share
        # can be re-queued: offsets IN FLIGHT on the lane (claimed, not
        # fully written) vs already WRITTEN to its transport -- the split
        # keeps `unwritten` exact across a resteal.
        self.rail_offs: dict = {}  # conn_id -> [offsets in flight]
        self.done_offs: dict = {}  # conn_id -> [offsets fully written]
        self.unwritten = len(self.pending)
        self.writers = 0         # feeders holding a chunk of this source
        self.local_done = False  # transmission begun (rndv semantics)
        self.counted = False     # sends_completed recorded once
        self.sacked = False
        self.failed = False

    def chunk_len(self, off: int) -> int:
        return min(self.chunk, self.total - off)

    def started(self) -> bool:
        return (self.local_done or bool(self.rail_offs)
                or bool(self.done_offs))

    def maybe_release(self) -> None:
        """Drop the payload pin once the source is settled AND no feeder
        is mid-frame on it -- a frame header already promised its chunk's
        bytes, so the view must stay valid until that frame completes."""
        if (self.sacked or self.failed) and self.writers <= 0:
            self.payload = None
            self.owner = None

    def settle(self, fires: list, reason: Optional[str],
               force: bool = False) -> None:
        """Terminal: fire the op outcome exactly once and release the
        payload pin (immediately when ``force`` -- terminal conn teardown,
        no feeder will ever touch it again)."""
        if reason is not None and not self.failed:
            self.failed = True
            if not self.local_done and self.fail is not None:
                fires.append(lambda f=self.fail, r=reason: f(r))
            self.local_done = True
        if force:
            self.writers = 0
        self.maybe_release()


class StripeFeeder:
    """One lane's persistent tx-queue item: streams its current chunk and
    claims the next from the group when it finishes (the work-stealing
    edge).  Speaks the same duck-typed tx protocol as TxData/TxCtl
    (core/conn.py); ``counted`` is pre-set so the generic pump accounting
    skips it -- the SOURCE owns per-message accounting."""

    __slots__ = ("group", "lane", "src", "chunk_off", "header", "chunk_end",
                 "written", "switch_after", "counted", "sess_seq",
                 "sess_nbytes", "e2e_ord", "claim_t0")

    def __init__(self, group: "RailGroup", lane: Lane):
        self.group = group
        self.lane = lane
        self.src: Optional[StripeSource] = None
        self.chunk_off = 0
        self.header = b""
        self.chunk_end = 0
        self.written = 0
        self.switch_after = False
        self.counted = True   # generic pump accounting: not a data item
        self.sess_seq = 0     # chunks are never seq-framed (idempotent)
        self.sess_nbytes = 0
        self.e2e_ord = 0
        self.claim_t0 = 0.0   # perf_counter at claim (lane EWMA sample)

    # ------------------------------------------------------------- claim
    def _claim(self, steal: bool = True) -> bool:
        nxt = self.group.claim_next(self.lane, steal)
        if nxt is None:
            return False
        src, off = nxt
        self.claim_t0 = time.perf_counter()
        self.src = src
        src.writers += 1
        self.chunk_off = off
        n = src.chunk_len(off)
        self.header = frames.pack_sdata_header(src.tag, src.msg_id, off,
                                               src.total, n)
        if self.lane.conn.csum_ok:
            # §19 integrity: every chunk frame is self-verifying -- the
            # prefix's crc_head covers header+sub-header (so routing is
            # validated before the chunk bytes land in a sink), crc_frame
            # the chunk bytes too.  Per-lane: each rail negotiated csum
            # in its own handshake.
            self.header = frames.pack_csum_for(
                self.header, src.payload[off:off + n]) + self.header
        self.chunk_end = off + n
        self.written = 0
        return True

    def _drop_src(self) -> None:
        src, self.src = self.src, None
        if src is not None:
            src.writers -= 1
            src.maybe_release()

    def _frame_total(self) -> int:
        return len(self.header) + (self.chunk_end - self.chunk_off)

    @property
    def off(self) -> int:
        """Generic tx-item progress (the close path's untouched-item
        check reads ``tx[0].off``): bytes of the current frame written."""
        return self.written

    @property
    def remaining(self) -> int:
        if self.src is None and not self._claim():
            return 0
        return self._frame_total() - self.written

    def tx_views(self, max_bytes: int) -> list:
        if self.src is None and not self._claim():
            return []
        views = []
        take = 0
        hlen = len(self.header)
        if self.written < hlen:
            h = memoryview(self.header)[self.written:]
            views.append(h)
            take = len(h)
        if take < max_bytes:
            pos = max(self.written - hlen, 0)
            sl = self.src.payload[self.chunk_off + pos:
                                  self.chunk_end]
            sl = sl[: max_bytes - take]
            if len(sl):
                views.append(sl)
        return views

    def advance(self, n: int, fires: list) -> None:
        if self.src is None:
            return
        self.written += n
        if n > 0 and not self.src.local_done:
            # Transmission begun: rndv-style local completion for the
            # whole striped message (DESIGN.md §17).
            self.group.first_progress(self.src, fires)
        if self.written >= self._frame_total():
            self.lane.note_chunk(self.chunk_end - self.chunk_off,
                                 time.perf_counter() - self.claim_t0)
            self.group.chunk_written(self.lane, self.src, self.chunk_off,
                                     fires)
            self._drop_src()
            self._claim()  # work-stealing: grab the next chunk now

    def write(self, conn, fires: list) -> bool:
        """Ring-transport path (sm-upgraded primary): stream chunk frames
        until the group runs dry or the ring fills."""
        while True:
            if self.src is None and not self._claim():
                return True
            hlen = len(self.header)
            while self.written < self._frame_total():
                if self.written < hlen:
                    chunk = memoryview(self.header)[self.written:]
                else:
                    pos = self.chunk_off + (self.written - hlen)
                    chunk = self.src.payload[pos: self.chunk_end]
                try:
                    n = conn._tx_write(chunk)
                except BlockingIOError:
                    if self.written > 0 and not self.src.local_done:
                        self.group.first_progress(self.src, fires)
                    return False
                self.written += n
                if not self.src.local_done:
                    self.group.first_progress(self.src, fires)
            self.lane.note_chunk(self.chunk_end - self.chunk_off,
                                 time.perf_counter() - self.claim_t0)
            self.group.chunk_written(self.lane, self.src, self.chunk_off,
                                     fires)
            self._drop_src()

    def cancel(self, fires: list, reason: str = REASON_CANCELLED) -> None:
        # The SOURCE owns the op callbacks; a dying lane's feeder is
        # inert -- rail_lost / group teardown settles the sources.
        self._drop_src()


class StripeAsm:
    """Receiver-side reassembly of one striped message: the matcher's
    InboundMsg plus the offset-dedup set that makes chunks idempotent."""

    __slots__ = ("msg_id", "tag", "total", "received", "msg", "offs")

    def __init__(self, msg_id: int, tag: int, total: int, msg):
        self.msg_id = msg_id
        self.tag = tag
        self.total = total
        self.received = 0
        self.msg = msg  # matching.InboundMsg (sink/discard/posted)
        self.offs: set = set()


class StripeRx:
    """Per-rail-group receive state, living on the primary conn: chunks
    from ANY rail of the group land in the same assembly table."""

    __slots__ = ("root", "asms", "done_ids", "done_fifo")

    def __init__(self, root):
        self.root = root  # primary TcpConn
        self.asms: dict = {}
        self.done_ids: set = set()
        self.done_fifo: deque = deque()

    def chunk_start(self, tag: int, msg_id: int, off: int, total: int,
                    chunk_len: int, fires: list):
        """Resolve one arriving chunk.  Returns the assembly to stream
        into, or None when the chunk must be drained (duplicate offset /
        already-completed message -- the caller re-SACKs those)."""
        if msg_id in self.done_ids:
            return None  # late resend of a completed message: re-SACK
        asm = self.asms.get(msg_id)
        if asm is None:
            worker = self.root.worker
            with worker.lock:
                msg, f = worker.matcher.on_message_start(tag, total)
            fires.extend(f)
            asm = self.asms[msg_id] = StripeAsm(msg_id, tag, total, msg)
        if off in asm.offs or off + chunk_len > total:
            return None  # duplicate (or malformed) chunk: drain + drop
        return asm

    def chunk_done(self, conn, asm: StripeAsm, off: int, chunk_len: int,
                   fires: list) -> None:
        """All bytes of one chunk ingested on ``conn``; completes the
        message (matcher + SACK) when it was the last."""
        if off in asm.offs:
            # A cross-rail duplicate was already streaming when its twin
            # completed (both passed chunk_start before either finished):
            # the bytes are identical, but the accounting must be
            # exactly-once or the assembly completes early and corrupt.
            return
        asm.offs.add(off)
        asm.received += chunk_len
        conn._ctr.stripe_chunks_rx += 1
        if asm.received < asm.total:
            return
        root = self.root
        msg = asm.msg
        msg.received = asm.total
        del self.asms[asm.msg_id]
        self.done_ids.add(asm.msg_id)
        self.done_fifo.append(asm.msg_id)
        while len(self.done_fifo) > DONE_LRU:
            self.done_ids.discard(self.done_fifo.popleft())
        # A cross-rail duplicate of some offset may still be mid-stream
        # on a sibling lane.  Completion hands the sink back to the user
        # (the receive's done fires below), so redirect those writes to
        # the drain path NOW -- the remaining bytes must never land in a
        # buffer the caller may already be reusing.
        for lane_conn in [root] + list(root.rails):
            st = lane_conn._rx_stripe
            if st is not None and st[0] is asm:
                lane_conn._rx_skip = st[2] - lane_conn._rx_stripe_got
                lane_conn._rx_stripe = None
                lane_conn._rx_stripe_got = 0
        worker = root.worker
        with worker.lock:
            fires.extend(worker.matcher.on_message_complete(msg))
        if msg.remote is not None:
            # §18 rendezvous delivery: resolve the descriptor record so
            # deferred flush ACKs release, and let the now-complete
            # message behave like ordinary staged data from here on.
            msg.remote = None
            root.fc_rx.pop(asm.msg_id, None)
            root.remote_resolved(msg, fires)
        self.sack(conn, asm.msg_id, asm.total, fires)
        if root._ring is not None and root.tr_id:
            # swscope: ONE end-to-end marker per striped message, on the
            # primary, ordinal = msg_id (shared wire state, so the pair
            # survives out-of-order assembly completion).
            root._ring.rec(swtrace.EV_E2E, asm.msg_id, root.conn_id,
                           asm.total, root.tr_id + ":sr")
        root._sess_commit()  # no-op off sessions (chunks are unsequenced)

    @staticmethod
    def sack(conn, msg_id: int, total: int, fires: list) -> None:
        if conn.alive and conn.sock is not None:
            conn.send_ctl(frames.pack_sack(msg_id, total), fires)

    def purge(self) -> None:
        """Primary died terminally: partial assemblies can never finish;
        drop them from the matcher so they cannot shadow live traffic."""
        worker = self.root.worker
        with worker.lock:
            for asm in self.asms.values():
                worker.matcher.purge_inflight(asm.msg)
        self.asms.clear()


class RailGroup:
    """TX scheduler for one railed connection (lives on the primary)."""

    __slots__ = ("primary", "lanes", "next_msg_id", "queue", "by_id")

    def __init__(self, primary):
        self.primary = primary
        self.lanes: list = [Lane(primary, 0)]
        self.next_msg_id = 1
        self.queue: deque = deque()  # sources with unclaimed chunks, FIFO
        self.by_id: dict = {}        # msg_id -> source until SACK/terminal

    def add_rail(self, conn) -> Lane:
        lane = Lane(conn, len(self.lanes))
        self.lanes.append(lane)
        return lane

    def live_lanes(self) -> list:
        return [ln for ln in self.lanes if ln.alive]

    def stripe_ok(self, nbytes: int, payload) -> bool:
        """Should this send stripe?  Needs a flat host view (chunks are
        random-offset slices), the threshold armed, and >1 live lane."""
        thr = config.stripe_threshold()
        return (thr > 0 and nbytes >= thr
                and isinstance(payload, memoryview)
                and len(self.live_lanes()) > 1)

    # ------------------------------------------------------------ submit
    def submit(self, tag: int, payload, done, fail, owner,
               fires: list) -> StripeSource:
        src = StripeSource(self.next_msg_id, tag, payload, done, fail,
                           owner, config.stripe_chunk())
        self.next_msg_id += 1
        self.by_id[src.msg_id] = src
        self.queue.append(src)
        self.primary.dirty = True
        self.dispatch(fires)
        return src

    def dispatch(self, fires: list) -> None:
        """Make sure every live lane has an active feeder and kick it.
        Feeders claim their FIRST chunk eagerly: one that cannot claim is
        never queued (a dry feeder parked in tx would stall every frame
        behind it -- the gather pump stops at feeders)."""
        for lane in self.live_lanes():
            feeder = lane.feeder
            conn = lane.conn
            if feeder is None or feeder not in conn.tx:
                feeder = StripeFeeder(self, lane)
                if not feeder._claim(steal=False):
                    break  # group dry: later lanes have nothing to claim
                lane.feeder = feeder
                conn.tx.append(feeder)
            conn.kick_tx(fires)

    def claim_next(self, lane: Lane, steal: bool = False):
        """The work-stealing heart: hand the next pending chunk (FIFO
        across sources) to whichever lane asked first.

        ``steal`` marks a refill claim (a feeder that just finished a
        chunk) as opposed to a dispatch claim.  Only steals may be
        declined by the weighted-tail policy: dispatch always feeds
        every live lane, so a declined chunk can never strand -- any
        requeue path (submit, rail death, NACK retransmit, session
        resume) goes through dispatch, and the fastest live lane never
        declines (its EWMA is the maximum by definition)."""
        while self.queue:
            src = self.queue[0]
            if not src.pending or src.sacked or src.failed:
                self.queue.popleft()
                continue
            break
        for src in self.queue:
            if not src.pending or src.sacked or src.failed:
                continue  # settled mid-queue: dropped when it reaches front
            if steal and self._decline_tail(lane, src):
                # Leave THIS message's tail to faster lanes, but keep
                # scanning: a slow lane declining msg N must still carry
                # the bulk of msg N+1 queued behind it -- idling the
                # lane entirely would halve throughput exactly when the
                # knob is meant to help.
                continue
            off = src.pending.popleft()
            src.rail_offs.setdefault(lane.conn.conn_id, []).append(off)
            lane.chunks_tx += 1
            return src, off
        return None

    def _decline_tail(self, lane: Lane, src: StripeSource) -> bool:
        """STARWAY_STRIPE_WEIGHTED tail bias (DESIGN.md §17): in the last
        chunks of a message -- where handing the final chunk to a slow
        lane makes that lane's drain time the WHOLE message's completion
        time -- a lane whose delivered-throughput EWMA sits below
        SLOW_FRACTION of the fastest live lane's declines the steal."""
        if not config.stripe_weighted() or lane.ewma_bps <= 0.0:
            return False
        live = self.live_lanes()
        if len(live) < 2 or len(src.pending) > len(live):
            return False  # not the tail (or nobody else to leave it to)
        best = max(ln.ewma_bps for ln in live)
        if lane.ewma_bps >= SLOW_FRACTION * best:
            return False
        lane.tail_declines += 1
        return True

    # -------------------------------------------------------- completion
    def first_progress(self, src: StripeSource, fires: list) -> None:
        if src.local_done:
            return
        src.local_done = True
        us = int((time.perf_counter() - src.t_post) * 1e6)
        self.primary._hists.send_local_us[swtrace.hist_bucket(us)] += 1
        if src.done is not None:
            fires.append(src.done)

    def chunk_written(self, lane: Lane, src: StripeSource, off: int,
                      fires: list) -> None:
        prim = self.primary
        prim._ctr.stripe_chunks_tx += 1
        prim.retx_offs.discard((src.msg_id, off))  # §19 retx satisfied
        cid = lane.conn.conn_id
        infl = src.rail_offs.get(cid)
        if infl is not None and off in infl:
            infl.remove(off)
            src.done_offs.setdefault(cid, []).append(off)
        src.unwritten -= 1
        if src.unwritten <= 0 and not src.pending and not src.counted:
            src.counted = True
            prim._ctr.sends_completed += 1
            if prim._ring is not None and prim.tr_id:
                prim._ring.rec(swtrace.EV_E2E, src.msg_id, prim.conn_id,
                               src.total, prim.tr_id + ":sx")

    def on_sack(self, msg_id: int, fires: list) -> None:
        src = self.by_id.pop(msg_id, None)
        if src is None or src.sacked:
            return
        if self.primary.retx_offs:
            self.primary.retx_offs = {
                t for t in self.primary.retx_offs if t[0] != msg_id}
        src.sacked = True
        # swpulse pin residency (§25): submit -> SACK is exactly how long
        # the payload stayed pinned by reference.
        us = int((time.perf_counter() - src.t_post) * 1e6)
        self.primary._hists.pin_us[swtrace.hist_bucket(us)] += 1
        src.settle(fires, None)
        self.primary.worker._on_stripe_sack(self.primary, fires)

    def has_unsacked(self, watermark: Optional[int] = None) -> bool:
        if watermark is None:
            return bool(self.by_id)
        return any(mid <= watermark for mid in self.by_id)

    # ----------------------------------------------------------- failure
    def rail_lost(self, conn, fires: list) -> None:
        """A secondary lane died: push its claimed-but-unacked chunks
        back to pending and let the survivors steal them.  The payload is
        pinned until SACK, so the resend is always legal; the receiver's
        offset dedup absorbs chunks that did land."""
        prim = self.primary
        self.lanes = [ln for ln in self.lanes if ln.conn is not conn]
        restolen = 0
        for src in self.by_id.values():
            infl = src.rail_offs.pop(conn.conn_id, None) or []
            done = src.done_offs.pop(conn.conn_id, None) or []
            if (not infl and not done) or src.failed or src.sacked:
                continue
            for off in infl:
                src.pending.append(off)  # never written: unwritten already
            for off in done:             # counts them
                src.pending.append(off)
                src.unwritten += 1       # written to the DEAD lane: back
            restolen += len(infl) + len(done)  # to unwritten for resend
            if src not in self.queue:
                self.queue.append(src)
        if restolen:
            prim._ctr.rail_resteals += restolen
            self.dispatch(fires)

    def expire(self, src: StripeSource, fires: list, reason: str) -> bool:
        """Deadline on a striped send: an unstarted source withdraws
        cleanly (returns False); a started one fails and the caller
        tears the group down (chunks already promised on the wire)."""
        if src.started():
            src.settle(fires, reason)
            return True
        self.by_id.pop(src.msg_id, None)
        try:
            self.queue.remove(src)
        except ValueError:
            pass
        src.settle(fires, reason)
        return False

    def redispatch_all(self, fires: list) -> None:
        """Session resume: re-dispatch every un-SACKed source from chunk
        zero across the (rebuilt) rail set.  The receiver's assemblies
        survived the outage keyed on the primary conn; its offset dedup
        and completed-id LRU make the wholesale resend exactly-once --
        the journal is per-message, never per-lane."""
        self.queue.clear()
        self.primary.retx_offs.clear()  # wholesale resend supersedes NACKs
        for msg_id in sorted(self.by_id):
            src = self.by_id[msg_id]
            if src.sacked or src.failed:
                continue
            src.pending = deque(range(0, src.total, src.chunk))
            src.rail_offs.clear()
            src.done_offs.clear()
            src.writers = 0  # the suspended incarnation's feeders are gone
            src.unwritten = len(src.pending)
            self.queue.append(src)
        if self.queue:
            self.dispatch(fires)

    def cancel_all(self, fires: list, reason: str) -> None:
        """Primary terminal teardown: settle every un-SACKed source.
        Entries stay in ``by_id`` (marked failed) so a flush barrier
        waiting on their SACKs observes the dead conn and fails instead
        of completing vacuously (engine.py stripe_waits)."""
        count = 0
        for src in self.by_id.values():
            if not src.sacked and not src.failed:
                src.settle(fires, reason, force=True)
                count += 1
        self.queue.clear()
        self.primary.retx_offs.clear()
        if count:
            self.primary._ctr.ops_cancelled += count
