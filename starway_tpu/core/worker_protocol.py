"""The engine worker contract — typed interface both engines implement.

This is the TPU build's equivalent of the reference's hand-written type stub
``src/starway/_bindings.pyi`` (SURVEY component #15): the contract the public
Python layer (:mod:`starway_tpu.api`) codes against.  The reference pins its
nanobind surface with a ``.pyi``; here the same role is played by structural
:class:`typing.Protocol` classes, which a test can additionally *enforce*
against both implementations (the reference's stub was unchecked).

Two implementations must satisfy these protocols and stay interoperable on
the wire (core/frames.py):

* the pure-Python event-loop engine (``core/engine.py``), and
* the C++ epoll engine behind a ctypes bridge (``native/sw_engine.cpp`` +
  ``core/native.py``).

Callback conventions (reference: src/starway/_bindings.pyi:30-90):

* ``done_callback`` for sends/flushes takes no arguments.
* ``done_callback`` for recvs takes ``(sender_tag, length)``.
* ``fail_callback`` takes a single ``reason`` string; cancellation reasons
  contain the substring ``"cancel"`` (pinned by tests/test_basic.py);
  deadline expiry reasons contain ``"timed out"`` (tests/test_faults.py).
* Connect callbacks take a status string, ``""`` meaning success.
* Callbacks may be invoked from the engine thread but never while any worker
  lock is held.
* ``timeout`` (seconds, ``None`` = unbounded) is an optional per-op
  deadline both engines honour: an op not settled when it fires fails with
  the stable ``"timed out"`` keyword and releases its transport/matcher
  resources (a timed-out receive's buffer is immediately repostable).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Protocol, runtime_checkable

#: Send/flush completion: no arguments.
DoneCallback = Callable[[], None]
#: Recv completion: (sender_tag, length).
RecvDoneCallback = Callable[[int, int], None]
#: Failure: human-readable reason (contains "cancel" when cancelled).
FailCallback = Callable[[str], None]
#: Connect result: "" on success, reason string on failure.
ConnectCallback = Callable[[str], None]


@runtime_checkable
class ConnectionLike(Protocol):
    """A peer connection as seen by the matcher and endpoint layer.

    Reference analogue: the ``ucp_ep_h`` + attribute snapshot inside
    ``ServerEndpoint`` (src/bindings/main.hpp:292-304).

    All identity fields are *read attributes* — plain data attributes on the
    Python engine's ``BaseConn`` (core/conn.py), properties on the native
    engine's ``NativeConn`` (core/native.py).  Only ``transports()`` is a
    method (endpoint.py calls it as one).
    """

    conn_id: int
    peer_name: str
    alive: bool
    mode: str
    local_addr: str
    local_port: int
    remote_addr: str
    remote_port: int

    def transports(self) -> list[tuple[str, str]]: ...


@runtime_checkable
class WorkerProtocol(Protocol):
    """Operations shared by client and server workers.

    Reference analogue: the common surface of ``_bindings.Client`` /
    ``_bindings.Server`` (src/starway/_bindings.pyi:23-90).
    """

    def submit_send(self, conn, view, tag: int,
                    done: DoneCallback, fail: FailCallback,
                    owner=None, timeout: Optional[float] = None) -> None: ...

    def post_recv(self, buf, tag: int, mask: int,
                  done: RecvDoneCallback, fail: FailCallback,
                  owner=None, timeout: Optional[float] = None) -> None: ...

    def submit_flush(self, done: DoneCallback, fail: FailCallback,
                     conns: Optional[Iterable] = None,
                     timeout: Optional[float] = None) -> None: ...

    def close(self, cb: DoneCallback) -> None: ...

    def force_close(self) -> None: ...

    def get_worker_address(self) -> bytes: ...

    def evaluate_perf(self, conn, msg_size: int) -> float: ...

    def evaluate_perf_detail(self, conn, msg_size: int) -> dict: ...


@runtime_checkable
class ClientWorkerProtocol(WorkerProtocol, Protocol):
    """Connecting-side worker (reference: _bindings.pyi:60-90)."""

    @property
    def primary_conn(self): ...

    def connect(self, addr: str, port: int, cb: ConnectCallback,
                timeout: Optional[float] = None) -> None: ...

    def connect_address(self, blob: bytes, cb: ConnectCallback,
                        timeout: Optional[float] = None) -> None: ...


@runtime_checkable
class ServerWorkerProtocol(WorkerProtocol, Protocol):
    """Accepting-side worker (reference: _bindings.pyi:23-58)."""

    def listen(self, addr: str, port: int) -> None: ...

    def listen_address(self) -> bytes: ...

    def set_accept_cb(self, cb) -> None: ...

    def list_clients(self) -> set: ...


__all__ = [
    "ConnectionLike",
    "WorkerProtocol",
    "ClientWorkerProtocol",
    "ServerWorkerProtocol",
    "DoneCallback",
    "RecvDoneCallback",
    "FailCallback",
    "ConnectCallback",
]
