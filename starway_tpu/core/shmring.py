"""Shared-memory ring transport: the same-host fast path ("sm").

The reference's UCX layer negotiates a shared-memory transport between
same-host processes whenever ``UCX_TLS`` allows it (reference:
benchmark.md:114-126 lists ``sm`` among the transports; posix/sysv shm are
UCX's loopback default).  This module is the TPU build's equivalent: a
pair of SPSC byte rings in a ``/dev/shm`` segment, negotiated over the
existing HELLO/HELLO_ACK handshake (core/frames.py) and carrying the exact
same framed byte stream as the TCP path -- the frame parser cannot tell the
transports apart.  The TCP connection stays open as the doorbell + liveness
channel (peer death is still detected by EOF/RST; wakeups are 1-byte
writes), so no busy-polling is needed: both engines stay event-driven.

Segment layout (all little-endian, offsets in bytes)::

    0    u64  magic      0x31676e69726d7773  ("swmring1")
    8    u64  nonce      random; echoed in HELLO to authenticate the segment
    16   u64  ring_size  bytes per direction, power of two
    24..63    reserved
    64   ring 0 header (connector->acceptor direction)
           +0   u64 tail              producer cursor, free-running
           +8   u64 (reserved)        legacy producer_blocked flag, unused
           +64  u64 head              consumer cursor, free-running
    192  ring 1 header (acceptor->connector direction), same shape
    320..383  reserved
    384             ring 0 data [ring_size]
    384+ring_size   ring 1 data [ring_size]

``head``/``tail`` live on separate cache lines (the producer writes tail and
reads head; the consumer the reverse).  Cursors are free-running u64s:
``avail = tail - head``, ``free = ring_size - avail``; data index is
``cursor & (ring_size - 1)``.  On x86/CPython the pure-Python cursor ops
lean on TSO: aligned 8-byte stores are atomic and store-store ordered,
which is exactly the data-before-tail publication this protocol needs.  On
other architectures Python cannot fence, so every cursor access routes
through the native lib's ``sw_atomic_load_u64``/``sw_atomic_store_u64``
(acquire/release; see :func:`_use_portable_atomics`) — ``config.
sm_enabled()`` refuses sm only when that lib is unavailable too.  The C++
engine implements the same layout with real atomics throughout and
carries sm on any architecture.  This layout is the cross-engine
contract: any change here must land in both engines (CLAUDE.md "two
engines, one contract").

Integrity records (DESIGN.md §19): when both peers negotiated ``csum``
(``STARWAY_INTEGRITY``), each producer write becomes one *slot record*
inside the same byte ring::

    u32 len     payload bytes that follow
    u32 crc     CRC32C over (u64 slot seqno LE || payload bytes)

The slot seqno is an implicit free-running per-direction counter both
sides maintain, so a stale or replayed region of ring memory can never
verify; the checksum over the payload catches torn/partial writes (a
consumer observing a published tail whose data stores it cannot yet see,
e.g. on a weakly-ordered host, reads a record that fails verification
instead of silently delivering garbage).  Records are written atomically
-- header+payload copied, then one tail publication -- so ``readable()``
always covers whole records; a record is sized to whatever fits, so the
stream semantics above the ring are unchanged.  Verification happens at
dequeue: a mismatch raises :class:`SmCorrupt` and the conn poisons with
the stable ``"corrupt"`` reason (core/conn.py).  The 8-byte record
header (``REC_HDR``) is cross-engine contract surface (``SM_REC_HDR`` in
sw_engine.cpp, machine-checked by ``python -m starway_tpu.analysis``).

Wakeup protocol: every cross-side wakeup rides the TCP socket, never shared
memory.  A producer that advances ``tail`` sends a doorbell byte (DB_DATA);
a producer that finds the ring full sends a *starving* byte (DB_STARVING)
and sleeps; the consumer, upon seeing a starving byte, drains the ring and
replies with a doorbell.  Because the signal is a send/recv syscall pair,
the sleeping side's next cursor read is ordered after the waking side's
cursor write (the kernel transition is a full barrier on both ends) -- the
classic store-load race of flag-based schemes cannot occur, in any
language, with no fence and no timed poll.  A doorbell that meets a full
socket buffer is queued and flushed on EPOLLOUT (core/conn.py), so the one
wakeup a sleeping producer depends on is never dropped.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import secrets
import struct

from . import frames

MAGIC = 0x31676E69726D7773  # b"swmring1" little-endian

_HDR = struct.Struct("<QQQ")  # magic, nonce, ring_size

GLOBAL_HDR = 64
RING_HDR = 128
DATA_OFF = GLOBAL_HDR + 2 * RING_HDR  # 384

OFF_TAIL = 0
OFF_HEAD = 64

# §19 integrity slot-record header: u32 payload len, u32 CRC32C(seqno ||
# payload) -- little-endian, leading every ring write when the conn
# negotiated "csum".  Cross-engine contract (SM_REC_HDR in sw_engine.cpp).
REC_HDR = 8
_REC = struct.Struct("<II")
_SEQ8 = struct.Struct("<Q")

SHM_DIR = "/dev/shm"

# 1 MiB keeps the ring + both working chunks cache-resident: measured on the
# dev box, 256K-1M rings stream at ~11-12 GB/s single-process while 4M+ rings
# fall to ~5 GB/s (DRAM eviction).  Large transfers are DRAM-bound anyway;
# small rings also bound the wakeup ping-pong granularity.
DEFAULT_RING = 1 << 20
MAX_RING = 1 << 30


def _use_portable_atomics() -> bool:
    """Route cursor accesses through the native lib's acquire/release
    atomics instead of raw mmap ops.  Needed off x86 (no TSO, Python can't
    fence); forceable on x86 via STARWAY_SM_FORCE_ATOMICS=1 so the
    portable path stays testable on this (x86) CI."""
    if os.environ.get("STARWAY_SM_FORCE_ATOMICS") == "1":
        return True
    import platform

    return platform.machine() not in ("x86_64", "AMD64")


def default_ring_size() -> int:
    raw = os.environ.get("STARWAY_SM_RING", "")
    if not raw:
        return DEFAULT_RING
    try:
        v = int(raw)
    except ValueError:
        return DEFAULT_RING
    # round up to a power of two within sane bounds
    v = max(4096, min(v, MAX_RING))
    return 1 << (v - 1).bit_length()


class SmCorrupt(OSError):
    """A §19 slot record failed verification at dequeue: torn write,
    bit-flip, or stale slot content.  The conn poisons ("corrupt")."""


class Ring:
    """One direction of the segment, viewed as a byte stream.

    Exactly one process calls :meth:`write` (the producer) and exactly one
    calls :meth:`read_into` (the consumer); both may inspect cursors.
    ``slotted`` (set via :meth:`ShmSegment.enable_integrity` once both
    peers negotiated ``csum``) switches both methods to the checksummed
    slot-record framing documented in the module docstring.
    """

    __slots__ = ("_u64", "_data", "size", "_hdr_idx", "_at", "_tail_addr",
                 "_head_addr", "slotted", "_tx_seq", "_rx_seq", "_rec_left",
                 "_rec_crc", "_rec_accum")

    def __init__(self, seg_mv: memoryview, hdr_off: int, data_off: int, size: int):
        self.slotted = False
        self._tx_seq = 0      # producer slot counter
        self._rx_seq = 0      # consumer slot counter
        self._rec_left = 0    # payload bytes left in the record being read
        self._rec_crc = 0
        self._rec_accum = 0
        # One u64 view over the whole segment: index = byte offset / 8.
        self._u64 = seg_mv.cast("B").cast("Q")
        self._data = seg_mv[data_off : data_off + size]
        self.size = size
        self._hdr_idx = hdr_off // 8
        self._at = None
        self._tail_addr = self._head_addr = 0
        if _use_portable_atomics():
            from . import native

            self._at = native.atomics()
            if self._at is None:
                # config.sm_enabled() refuses sm before it gets here; this
                # guards direct Ring constructions (tests, future callers).
                raise RuntimeError(
                    "sm on a non-TSO host needs the native lib's cursor "
                    "atomics (core/native.py:atomics)")
            # Address only -- the from_buffer export is dropped immediately
            # so it cannot pin the segment against close; the mapping (and
            # thus the address) outlives this Ring by construction.
            anchor = ctypes.c_char.from_buffer(seg_mv)
            base = ctypes.addressof(anchor)
            del anchor
            self._tail_addr = base + hdr_off + OFF_TAIL
            self._head_addr = base + hdr_off + OFF_HEAD

    # cursor accessors: on x86/CPython these are single aligned 8-byte mmap
    # ops (atomic + store-ordered under TSO); elsewhere they route through
    # the native acquire/release atomics (one memory-ordering contract with
    # the C++ engine's SmRing on the same segment).
    @property
    def tail(self) -> int:
        if self._at is not None:
            return self._at[0](self._tail_addr)
        return self._u64[self._hdr_idx + OFF_TAIL // 8]

    @tail.setter
    def tail(self, v: int) -> None:
        if self._at is not None:
            self._at[1](self._tail_addr, v)
            return
        self._u64[self._hdr_idx + OFF_TAIL // 8] = v

    @property
    def head(self) -> int:
        if self._at is not None:
            return self._at[0](self._head_addr)
        return self._u64[self._hdr_idx + OFF_HEAD // 8]

    @head.setter
    def head(self, v: int) -> None:
        if self._at is not None:
            self._at[1](self._head_addr, v)
            return
        self._u64[self._hdr_idx + OFF_HEAD // 8] = v

    def readable(self) -> int:
        return self.tail - self.head

    def free(self) -> int:
        return self.size - (self.tail - self.head)

    # ------------------------------------------------------------------ I/O
    def _put(self, cursor: int, src) -> None:
        """Copy ``src`` into the data area at ``cursor`` (wrapping); the
        caller publishes the tail afterwards."""
        n = len(src)
        idx = cursor & (self.size - 1)
        first = min(n, self.size - idx)
        self._data[idx : idx + first] = src[:first]
        if n > first:
            self._data[: n - first] = src[first:n]

    def _take(self, cursor: int, dst) -> None:
        """Copy ``len(dst)`` bytes out of the data area at ``cursor``
        (wrapping); the caller advances the head afterwards."""
        n = len(dst)
        idx = cursor & (self.size - 1)
        first = min(n, self.size - idx)
        dst[:first] = self._data[idx : idx + first]
        if n > first:
            dst[first:n] = self._data[: n - first]

    def write(self, src: memoryview) -> int:
        """Producer: append up to ``len(src)`` bytes; returns bytes written
        (0 when full).  Data is copied before the tail store publishes it.
        Slotted mode frames the accepted bytes as ONE checksummed record
        (header + payload, single tail publication: whole-record
        visibility)."""
        tail = self.tail
        free = self.size - (tail - self.head)
        if not self.slotted:
            n = min(len(src), free)
            if n <= 0:
                return 0
            self._put(tail, src[:n])
            self.tail = tail + n
            return n
        if free <= REC_HDR:
            return 0
        n = min(len(src), free - REC_HDR)
        if n <= 0:
            return 0
        body = src[:n]
        crc = frames.crc32c(body, frames.crc32c(_SEQ8.pack(self._tx_seq)))
        self._tx_seq += 1
        self._put(tail, _REC.pack(n, crc))
        self._put(tail + REC_HDR, body)
        self.tail = tail + REC_HDR + n
        return n

    def read_into(self, dst: memoryview) -> int:
        """Consumer: read up to ``len(dst)`` bytes; returns bytes read.
        Slotted mode walks the record framing, folds the payload CRC as
        bytes leave the ring, and raises :class:`SmCorrupt` at a record
        boundary whose checksum (over seqno + payload) does not verify --
        detection happens AT DEQUEUE, before the bytes can be parsed."""
        if not self.slotted:
            head = self.head
            n = min(len(dst), self.tail - head)
            if n <= 0:
                return 0
            self._take(head, dst[:n])
            self.head = head + n
            return n
        total = 0
        while total < len(dst):
            head = self.head
            avail = self.tail - head
            if self._rec_left == 0:
                if avail < REC_HDR:
                    break  # producers publish whole records: ring idle
                hdr = bytearray(REC_HDR)
                self._take(head, hdr)
                ln, crc = _REC.unpack(hdr)
                if ln == 0 or ln > self.size:
                    raise SmCorrupt("sm slot record header corrupt "
                                    f"(len={ln})")
                self.head = head + REC_HDR
                self._rec_left = ln
                self._rec_crc = crc
                self._rec_accum = frames.crc32c(_SEQ8.pack(self._rx_seq))
                self._rx_seq += 1
                continue
            n = min(len(dst) - total, self._rec_left, avail)
            if n <= 0:
                break
            out = dst[total : total + n]
            self._take(head, out)
            self._rec_accum = frames.crc32c(out, self._rec_accum)
            self.head = head + n
            self._rec_left -= n
            total += n
            if self._rec_left == 0 and self._rec_accum != self._rec_crc:
                raise SmCorrupt("sm slot record checksum mismatch "
                                f"(slot {self._rx_seq - 1})")
        return total

    def release(self) -> None:
        # Null the atomics path too: a post-close cursor access must raise
        # (like the mmap path's released-memoryview ValueError), not call
        # sw_atomic_load_u64 on an unmapped page and segfault the process.
        self._at = None
        self._tail_addr = self._head_addr = 0
        self._data.release()
        self._u64.release()


def decode_sm_records(data, ring_size: int = DEFAULT_RING) -> str:
    """Reference decoder for the §19 slot-record framing: the exact
    accept/reject/short outcome of :meth:`Ring.read_into`'s slotted walk
    (and the C++ engine's ``SmRing::read_into``) over a flat byte region,
    as one canonical string (frames.fmt_decode).  The slot seqno is the
    implicit free-running counter starting at 0, so a record lifted from
    a stale/replayed region of ring memory fails its checksum here
    exactly as it does at live dequeue.  Fed identical adversarial
    buffers by the `wirefuzz` analysis pass (mode ``smrec``) on both
    engines -- divergence is a contract finding (DESIGN.md §21)."""
    buf = bytes(data)  # swcheck: allow(hotpath-copy): bounded fuzz/gate input, never a data path
    n = len(buf)
    pos = 0
    consumed = 0
    seq = 0
    entries: list = []
    while True:
        if n - pos == 0:
            return frames.fmt_decode("ok", consumed, entries)
        if n - pos < REC_HDR:
            return frames.fmt_decode("short:rec-header", consumed, entries)
        ln, crc = _REC.unpack(buf[pos:pos + REC_HDR])
        if ln == 0 or ln > ring_size:
            # Garbled record header: SmCorrupt / -1 at live dequeue.
            return frames.fmt_decode("reject(sm record header)",
                                     consumed, entries)
        if pos + REC_HDR + ln > n:
            return frames.fmt_decode("short:rec-body", consumed, entries)
        accum = frames.crc32c(buf[pos + REC_HDR:pos + REC_HDR + ln],
                              frames.crc32c(_SEQ8.pack(seq)))
        if accum != crc:
            return frames.fmt_decode("reject(sm record checksum)",
                                     consumed, entries)
        seq += 1
        pos += REC_HDR + ln
        consumed = pos
        entries.append(f"r:{ln}")


class ShmSegment:
    """A mapped segment holding both rings of one connection.

    The connector *creates* (and offers the name in HELLO); the acceptor
    *attaches* and validates magic+nonce, then the name is unlinked by
    whichever side gets there first -- after both are mapped the name is
    dead weight and the pages live until the last mapping goes away.
    """

    __slots__ = ("key", "nonce", "ring_size", "_mm", "_mv", "rings", "creator")

    def __init__(self, key: str, nonce: int, ring_size: int, mm: mmap.mmap, creator: bool):
        self.key = key
        self.nonce = nonce
        self.ring_size = ring_size
        self._mm = mm
        self._mv = memoryview(mm)
        self.rings = (
            Ring(self._mv, GLOBAL_HDR, DATA_OFF, ring_size),
            Ring(self._mv, GLOBAL_HDR + RING_HDR, DATA_OFF + ring_size, ring_size),
        )
        self.creator = creator

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def create(cls, key_hint: str, ring_size: int | None = None) -> "ShmSegment":
        size = ring_size or default_ring_size()
        if size & (size - 1):
            raise ValueError("ring size must be a power of two")
        # Mirror the attach-side validation: the hint feeds a /dev/shm path,
        # so strip anything that could escape the directory ('/', '..').
        key_hint = "".join(ch for ch in key_hint if ch.isalnum() or ch in "_-")
        key = f"sw-{key_hint}-{secrets.token_hex(4)}"
        path = os.path.join(SHM_DIR, key)
        total = DATA_OFF + 2 * size
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        nonce = secrets.randbits(64)
        _HDR.pack_into(mm, 0, MAGIC, nonce, size)
        return cls(key, nonce, size, mm, creator=True)

    @classmethod
    def attach(cls, key: str, nonce: int, ring_size: int) -> "ShmSegment":
        """Map an offered segment; raises on any mismatch (caller falls back
        to TCP)."""
        if "/" in key or not key.startswith("sw-"):
            raise ValueError(f"bad sm key {key!r}")
        if ring_size & (ring_size - 1) or not 4096 <= ring_size <= MAX_RING:
            raise ValueError(f"bad sm ring size {ring_size}")
        path = os.path.join(SHM_DIR, key)
        total = DATA_OFF + 2 * ring_size
        fd = os.open(path, os.O_RDWR)
        try:
            st = os.fstat(fd)
            # /dev/shm is world-writable: only map segments our own uid
            # created, or a hostile local process could offer a file it can
            # truncate under us later (SIGBUS on the next ring access).
            if st.st_uid != os.geteuid():
                raise ValueError("sm segment owned by another uid")
            if st.st_size != total:
                raise ValueError("sm segment size mismatch")
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        magic, got_nonce, got_size = _HDR.unpack_from(mm, 0)
        if magic != MAGIC or got_nonce != nonce or got_size != ring_size:
            mm.close()
            raise ValueError("sm segment header mismatch")
        return cls(key, nonce, ring_size, mm, creator=False)

    def enable_integrity(self) -> None:
        """Switch both rings to §19 checksummed slot records.  Decided by
        the csum handshake and called before any ring byte flows -- both
        sides must agree or the framings cannot interoperate."""
        for r in self.rings:
            r.slotted = True

    def unlink(self) -> None:
        try:
            os.unlink(os.path.join(SHM_DIR, self.key))
        except OSError:
            pass

    def close(self) -> None:
        for r in self.rings:
            try:
                r.release()
            except Exception:
                pass
        try:
            self._mv.release()
        except Exception:
            pass
        try:
            self._mm.close()
        except Exception:
            pass

    # ------------------------------------------------------- role selection
    def tx_rx(self, creator: bool) -> tuple[Ring, Ring]:
        """(producer ring, consumer ring) for this side.  Ring 0 carries
        connector->acceptor traffic."""
        return (self.rings[0], self.rings[1]) if creator else (self.rings[1], self.rings[0])
