"""Connection objects: the per-peer data plane of the host runtime.

The reference models a peer as a ``ucp_ep_h`` driven by a busy-poll progress
thread (reference: src/bindings/main.cpp:361-468, 1126-1268).  The TPU build
replaces that with two connection kinds, both driven by an event-driven
engine thread (see core/engine.py -- no busy-poll; the host CPU belongs to
XLA dispatch, not to spin loops):

* :class:`TcpConn` -- framed stream socket (core/frames.py).  This is the
  bootstrap / cross-process / DCN-adjacent path and carries the reference's
  flush-vs-close delivery semantics (tests/test_basic.py:190-415).  When
  both peers share a host and ``STARWAY_TLS`` allows ``sm``, the handshake
  upgrades the conn to shared-memory rings (core/shmring.py): the same
  framed byte stream flows through the rings, the socket stays open as the
  doorbell + liveness channel, and every semantic above is unchanged --
  the frame parser reads from ``_rx_read`` and cannot tell the transports
  apart.  This mirrors UCX negotiating posix shm over the same API when
  ``UCX_TLS`` includes ``sm`` (reference: benchmark.md:114-126).
* :class:`InprocConn` -- same-process fast path.  Delivery is a single copy
  into the matched receive buffer under the receiver's lock; device-buffer
  (jax.Array) payloads hand over array references and move HBM-to-HBM over
  ICI with no host serialization.

Send completion semantics (mirrors UCX eager/RNDV, SURVEY.md section 5
"Distributed communication backend"):

* eager (payload <= STARWAY_RNDV_THRESHOLD): the send future resolves once
  the payload is fully handed to the transport (written to the kernel socket
  / delivered in-process).  A graceful close afterwards still delivers.
* rendezvous (larger): the send future resolves when transmission has begun
  (header on the wire).  Delivery is only guaranteed after ``aflush`` /
  ``aflush_ep``; closing with the payload still in flight aborts the
  connection and the peer's receive never completes -- exactly the behaviour
  the reference pins with 8 GiB in-flight sends (tests/test_basic.py:190-339).
"""

from __future__ import annotations

import itertools
import logging
import socket
import time
from collections import deque
from typing import Optional

from .. import config, perf
from ..errors import REASON_CANCELLED, REASON_CORRUPT, REASON_NOT_CONNECTED
from . import frames, state, swtrace
from .lane import RailGroup, StripeFeeder, StripeRx
from .matching import InboundMsg
from .shmring import SmCorrupt

logger = logging.getLogger("starway_tpu")

_conn_ids = itertools.count(1)

TX_CHUNK = 1 << 22  # 4 MiB socket write granularity
RX_CHUNK = 1 << 22
# Gathered-write bounds for the socket TX pump (kick_tx): views per sendmsg
# (well under IOV_MAX=1024) and bytes per pass.  Mirrors the native engine's
# tcp_tx_gather (native/sw_engine.cpp) -- one syscall covers a burst of
# queued small frames plus the front of a large payload.
GATHER_IOV = 64

# §19 integrity plane: frame types exempt from the negotiated per-frame
# checksum -- the handshake pair predates negotiation and the T_SEQ
# session prefix glues OUTSIDE the checksum envelope (wire order
# [SEQ][CSUM][frame]; a corrupted SEQ surfaces as a seq gap, which is
# already a recoverable fault).  Everything else on a csum conn must be
# announced by a T_CSUM or the stream is poisoned.  The sets live in
# frames.py (one decode contract: this parser, frames.decode_stream, and
# the native kCsumExempt[]/kCsumBody[] -- diffed by the wirefuzz pass).
_CSUM_EXEMPT = frames.CSUM_EXEMPT
# Frame types whose bytes continue past the header on the wire (given
# header ``b`` > 0): the full-frame CRC verifies at their last byte;
# every other type is header-only and verifies at dispatch.
_CSUM_BODY = frames.CSUM_BODY

# Doorbell byte values on an sm-upgraded conn's socket (the contract shared
# with the native engine -- native/sw_engine.cpp).  Any byte wakes the peer
# (drain socket, pump ring, retry tx); DB_STARVING additionally asks the
# peer to reply with a doorbell after it drains, which is the wakeup for a
# producer sleeping on a full ring.  Wakeups ride the socket exclusively:
# the send/recv syscall pair orders the cursor stores between processes, so
# the sleep needs no shared flag and no timed poll (see shmring.py).
DB_DATA = 1
DB_STARVING = 2

# swpulse (DESIGN.md §25): sink for items built without a worker-backed
# histogram set (tests constructing bare conns) -- the bump sites then
# never branch.  Mirrors the ``_ctr`` fallback in BaseConn.__init__.
_ORPHAN_HISTS = swtrace.Hists()


class TxData:
    """An outgoing tagged message (header + zero-copy payload view).

    ``payload`` is either a flat host ``memoryview`` or a *chunked* payload
    duck type (``nbytes`` + ``host_chunk(pos) -> (chunk_start, view)``, see
    device.py DevicePayload.chunked): the TX pump then materialises host
    bytes one chunk at a time, and the payload prefetches the next chunk's
    device-to-host copy before returning the current one -- staging overlaps
    transmission (DESIGN.md §12).  Either way the wire sees one ordinary
    DATA frame.
    """

    # __weakref__: deadline timers (core/engine.py) hold queued sends
    # weakly, so a completed send's payload is not pinned until its timer
    # would have fired.
    __slots__ = ("header", "payload", "nbytes", "tag", "off", "done", "fail",
                 "owner", "rndv", "local_done", "switch_after", "counted",
                 "sess_seq", "sess_nbytes", "e2e_ord", "t_post", "t_park",
                 "hists", "_chunk_start", "_chunk_view", "__weakref__")

    def __init__(self, tag: int, payload, done, fail, owner,
                 hists: Optional[swtrace.Hists] = None):
        if isinstance(payload, memoryview):
            self.nbytes = len(payload)
            self._chunk_start = 0
            self._chunk_view: Optional[memoryview] = payload
        else:  # chunked payload duck type
            self.nbytes = int(payload.nbytes)
            self._chunk_start = 0
            self._chunk_view = None
        self.header = frames.pack_data_header(tag, self.nbytes)
        self.payload = payload
        self.tag = tag
        self.off = 0
        self.done = done
        self.fail = fail
        self.owner = owner
        self.rndv = self.nbytes > config.rndv_threshold()
        self.local_done = False
        self.switch_after = False
        self.counted = False  # sends_completed recorded (replay must not re-count)
        self.sess_seq = 0     # session sequence number (0 = unframed)
        self.sess_nbytes = 0  # journal accounting (prefix + header + payload)
        self.e2e_ord = 0      # swscope wire ordinal (assigned at first full TX)
        # swpulse (DESIGN.md §25): creation stamp for the send_local_us
        # distribution, park stamp for park_us (0 = never parked).
        self.t_post = time.perf_counter()
        self.t_park = 0.0
        self.hists = hists if hists is not None else _ORPHAN_HISTS

    def _pulse_local(self) -> None:
        """One send_local_us bump at the local-completion transition
        (§25): a clock read + an array increment, nothing else."""
        us = int((time.perf_counter() - self.t_post) * 1e6)
        self.hists.send_local_us[swtrace.hist_bucket(us)] += 1

    def _pulse_unpark(self) -> None:
        """One park_us bump as a §18-parked send leaves the park queue."""
        if self.t_park:
            us = int((time.perf_counter() - self.t_park) * 1e6)
            self.hists.park_us[swtrace.hist_bucket(us)] += 1
            self.t_park = 0.0

    @property
    def total(self) -> int:
        return len(self.header) + self.nbytes

    @property
    def remaining(self) -> int:
        return self.total - self.off

    def payload_slice(self, pos: int, limit: int) -> memoryview:
        """Up to ``limit`` payload bytes starting at ``pos``, never crossing
        a staging-chunk boundary."""
        view, start = self._chunk_view, self._chunk_start
        if view is None or not (start <= pos < start + len(view)):
            start, view = self.payload.host_chunk(pos)
            self._chunk_start, self._chunk_view = start, view
        rel = pos - start
        return view[rel : rel + limit]

    def tx_views(self, max_bytes: int) -> list:
        """Unwritten views for the gathered socket pump (header remnant +
        the current payload chunk), bounded by ``max_bytes``."""
        views = []
        off, hlen, take = self.off, len(self.header), 0
        if off < hlen:
            h = memoryview(self.header)[off:]
            views.append(h)
            take = len(h)
            off = hlen
        if take < max_bytes and off < self.total:
            sl = self.payload_slice(off - hlen, min(TX_CHUNK, max_bytes - take))
            if len(sl):
                views.append(sl)
        return views

    def advance(self, n: int, fires: list) -> None:
        self.off += n
        self._maybe_local_complete(fires)
        if self.off >= self.total and not self.local_done:
            self.local_done = True
            self._pulse_local()
            if self.done is not None:
                fires.append(self.done)

    def write(self, conn: "TcpConn", fires: list) -> bool:
        """Write as much as possible (ring transport).  True when fully
        written.  (The socket transport uses the gathered pump in kick_tx.)"""
        hlen = len(self.header)
        while self.off < self.total:
            if self.off < hlen:
                # Header + first payload chunk in one gathered write: small
                # messages cost one syscall (and one TCP segment), not two.
                views = [memoryview(self.header)[self.off :]]
                if self.nbytes:
                    views.append(self.payload_slice(0, TX_CHUNK))
                try:
                    n = conn._tx_writev(views)
                except BlockingIOError:
                    self._maybe_local_complete(fires)
                    return False
            else:
                p = self.off - hlen
                try:
                    n = conn._tx_write(self.payload_slice(p, TX_CHUNK))
                except BlockingIOError:
                    self._maybe_local_complete(fires)
                    return False
            self.off += n
            self._maybe_local_complete(fires)
        if not self.local_done:
            self.local_done = True
            self._pulse_local()
            if self.done is not None:
                fires.append(self.done)
        return True

    def _maybe_local_complete(self, fires: list) -> None:
        # Rendezvous local completion: transmission begun (header written).
        if self.rndv and not self.local_done and self.off >= len(self.header):
            self.local_done = True
            self._pulse_local()
            if self.done is not None:
                fires.append(self.done)

    def cancel(self, fires: list, reason: str = REASON_CANCELLED) -> None:
        if not self.local_done:
            self.local_done = True
            if self.fail is not None:
                fires.append(lambda f=self.fail, r=reason: f(r))

    # ------------------------------------------------------------ session
    def sess_wrap(self, seq: int, prefix: bytes) -> None:
        """Frame for the session layer: embed the T_SEQ prefix and, for
        eager flat payloads, snapshot the bytes -- the user may legally
        reuse the buffer once ``done`` fires, and a later replay must
        resend what was originally promised.  Rendezvous payloads stay
        by-reference (delivery is only promised after a flush; the
        journal pins the payload object until the peer ACKs -- the §14
        stability contract).  Eager payloads are always flat host views
        here: device.py keeps the lazy-chunked pipeline off session
        conns, so the snapshot below covers every eager frame."""
        self.sess_seq = seq
        self.header = prefix + self.header
        if not self.rndv and isinstance(self.payload, memoryview):
            # swcheck: allow(hotpath-copy): journal must own eager payload bytes past local completion (session opt-in)
            snap = memoryview(bytes(self.payload))
            self.payload = snap
            self._chunk_view = snap
            self._chunk_start = 0
            self.owner = None
        self.sess_nbytes = self.total

    def reset_for_replay(self) -> None:
        self.off = 0
        self._chunk_start = 0
        self._chunk_view = self.payload if isinstance(self.payload, memoryview) else None


class TxDevpull:
    """A DEVPULL descriptor send: a tagged message whose payload stays on
    the sender's transfer server (device.py).  Local completion = the
    descriptor fully handed to the transport (eager semantics: the array
    itself is already registered for pull)."""

    __slots__ = ("data", "off", "done", "fail", "owner", "switch_after",
                 "counted", "sess_seq", "sess_nbytes", "e2e_ord")

    def __init__(self, data: bytes, done, fail, owner):
        self.data = data
        self.off = 0
        self.done = done
        self.fail = fail
        self.owner = owner
        self.switch_after = False
        self.counted = False
        self.sess_seq = 0
        self.sess_nbytes = 0
        self.e2e_ord = 0

    @property
    def remaining(self) -> int:
        return len(self.data) - self.off

    def tx_views(self, max_bytes: int) -> list:
        v = memoryview(self.data)[self.off : self.off + max_bytes]
        return [v] if len(v) else []

    def advance(self, n: int, fires: list) -> None:
        self.off += n
        if self.off >= len(self.data) and self.done is not None:
            done, self.done = self.done, None
            fires.append(done)

    def write(self, conn: "TcpConn", fires: list) -> bool:
        while self.off < len(self.data):
            try:
                n = conn._tx_write(memoryview(self.data)[self.off :])
            except BlockingIOError:
                return False
            self.off += n
        if self.done is not None:
            done, self.done = self.done, None
            fires.append(done)
        return True

    def cancel(self, fires: list, reason: str = REASON_CANCELLED) -> None:
        if self.done is not None and self.fail is not None:
            fail, self.fail = self.fail, None
            self.done = None
            fires.append(lambda r=reason: fail(r))

    def sess_wrap(self, seq: int, prefix: bytes) -> None:
        self.sess_seq = seq
        self.data = prefix + self.data
        self.sess_nbytes = len(self.data)

    def reset_for_replay(self) -> None:
        self.off = 0


class RtsHandle:
    """Receiver-side §18 rendezvous offer (the sender's T_RTS): the
    matcher treats it exactly like a devpull descriptor -- duck-typed
    ``started`` / ``start(msg)`` invoked via fire thunks outside locks,
    flush-barrier deferral and force-start included.  ``start`` hops to
    the engine thread, which picks the sink, pre-registers the assembly,
    and answers CTS."""

    __slots__ = ("conn", "msg_id", "total", "tag", "started", "msg")

    def __init__(self, conn, msg_id: int, total: int, tag: int):
        self.conn = conn
        self.msg_id = msg_id
        self.total = total
        self.tag = tag
        self.started = False
        self.msg = None

    def start(self, msg) -> None:
        worker = self.conn.worker
        with worker.lock:
            if self.started or worker.status != state.RUNNING:
                return
            self.started = True
            worker._busy += 1
            worker.ops.append(("fc_cts", self.conn, msg))
        worker._wake()


class TxCtl:
    """A small control frame (HELLO/HELLO_ACK/FLUSH/FLUSH_ACK).

    ``switch_after`` marks the sm transport switch point (the HELLO_ACK):
    once this item finishes writing to the socket, TX flips to the ring --
    items queued behind it ride the ring even while it is still draining,
    so stream bytes can never follow the ACK onto the socket.
    """

    __slots__ = ("data", "off", "switch_after", "sess_seq", "sess_nbytes")

    def __init__(self, data: bytes, switch_after: bool = False):
        self.data = data
        self.off = 0
        self.switch_after = switch_after
        self.sess_seq = 0     # nonzero on sequenced session ctl (FLUSH/FLUSH_ACK)
        self.sess_nbytes = 0

    @property
    def remaining(self) -> int:
        return len(self.data) - self.off

    def tx_views(self, max_bytes: int) -> list:
        v = memoryview(self.data)[self.off : self.off + max_bytes]
        return [v] if len(v) else []

    def advance(self, n: int, fires: list) -> None:
        self.off += n

    def write(self, conn: "TcpConn", fires: list) -> bool:
        while self.off < len(self.data):
            try:
                n = conn._tx_write(memoryview(self.data)[self.off :])
            except BlockingIOError:
                return False
            self.off += n
        return True

    def cancel(self, fires: list, reason: str = REASON_CANCELLED) -> None:
        pass

    def sess_wrap(self, seq: int, prefix: bytes) -> None:
        self.sess_seq = seq
        self.data = prefix + self.data
        self.sess_nbytes = len(self.data)

    def reset_for_replay(self) -> None:
        self.off = 0


class BaseConn:
    def __init__(self, worker, mode: str):
        self.conn_id = next(_conn_ids)
        self.worker = worker
        # swtrace counters + per-worker stage scope, cached so the data
        # path pays one attribute load per sample (DESIGN.md §13).
        self._ctr = getattr(worker, "counters", None) or swtrace.Counters()
        # swpulse distributions (DESIGN.md §25), cached like the counters.
        self._hists = getattr(worker, "hists", None) or _ORPHAN_HISTS
        self._scope = getattr(worker, "stage_scope", None)
        # swscope (DESIGN.md §15): the worker's trace ring (None = dark),
        # the negotiated trace-conn id ("tr" handshake key; "" until both
        # sides confirm), and the per-direction wire ordinals that pair
        # send-side and recv-side EV_E2E events across processes.
        self._ring = getattr(worker, "_trace", None)
        # swrefine protocol-event channel (DESIGN.md §22): the same ring,
        # armed only by STARWAY_PROTO_TRACE / STARWAY_MONITOR -- the seed
        # path (and plain STARWAY_TRACE runs) pay one `is None` check per
        # frame and emit nothing.
        self._proto = self._ring if swtrace.proto_active() else None
        self.tr_id = ""
        self.tx_e2e_ord = 0
        self.rx_e2e_ord = 0
        # Best clock-offset estimate for the peer (EV_CLOCK samples from
        # timestamped PING/PONG round trips): peer ~= local + offset.
        self.clock_off_us = 0
        self.clock_err_us = 0  # 0 = no sample yet
        self.mode = mode  # "socket" | "address"
        self.alive = True
        self.peer_name = ""
        self.local_addr = ""
        self.local_port = 0
        self.remote_addr = ""
        self.remote_port = 0
        self.flush_seq = 0
        self.flush_acked = 0
        # Delivery-barrier accounting: ``dirty`` = tagged data handed to this
        # conn that no completed flush has covered yet.  A dead+dirty conn
        # fails flush instead of passing it vacuously.
        self.dirty = False
        self._data_counter = 0
        self._flush_marks: dict[int, int] = {}

    def alloc_flush_seq(self) -> int:
        self.flush_seq += 1
        return self.flush_seq


class TcpConn(BaseConn):
    kind = "tcp"

    def __init__(self, worker, sock: socket.socket, mode: str, handshaken: bool):
        super().__init__(worker, mode)
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.sock = sock
        self.handshaken = handshaken  # False on server side until HELLO arrives
        # Peer-liveness keepalive (frames.py PING/PONG): negotiated via
        # "ka": "ok" in the handshake; last_rx is proof-of-life (any inbound
        # bytes -- stream, ring, or doorbell -- refresh it).
        self.ka_ok = False
        self.last_rx = time.monotonic()
        self.tx: deque = deque()
        self._registered = False
        self._want_write = False
        # rx parser state
        self._hdr = bytearray(frames.HEADER_SIZE)
        self._hdr_got = 0
        self._ctl: Optional[tuple] = None  # (ftype, body, got, header_a)
        self._rx_msg: Optional[InboundMsg] = None
        self._scratch: Optional[bytearray] = None
        # Shared-memory upgrade state (core/shmring.py).  ``sm_active`` =
        # negotiated; ``_tx_via_ring`` flips once everything queued before
        # the switch (the HELLO_ACK) has drained to the socket, so stream
        # bytes never interleave across transports.
        self._sm = None
        self.sm_tx = None
        self.sm_rx = None
        self.sm_active = False
        self.sm_negotiated = False  # sticky: survives teardown for introspection
        self._tx_via_ring = False
        # Doorbell bytes that hit a full socket buffer: flushed on EPOLLOUT.
        # A starving byte (DB_STARVING) is the only wakeup a ring-blocked
        # producer gets, so doorbells must never be silently dropped.
        self._db_out = bytearray()
        self._tx_want_sock = False
        # PJRT pull extension (frames.py T_DEVPULL): negotiated in the
        # handshake; descriptors received on this conn that have not yet
        # resolved (pull done/failed) hold back FLUSH_ACKs so the sender's
        # flush barrier covers pulled payloads too.
        self.devpull_ok = False
        self._remote_msgs: set = set()
        self._deferred_flush_acks: list = []
        # Resilient-session state (core/session.py; negotiated via the
        # "sess" handshake key).  None on seed-parity conns: every session
        # hook below is a single `is None` check.
        # Multi-rail striping (core/lane.py; DESIGN.md §17).  On a
        # PRIMARY conn: ``rails`` holds the attached secondary conns,
        # ``stripe``/``stripe_rx`` the lazily-created TX scheduler and RX
        # reassembly tables.  On a SECONDARY: ``rail_parent`` points at
        # the primary.  All None/empty on seed-parity conns.
        self.rails: list = []
        self.rail_parent: Optional["TcpConn"] = None
        self.rails_ok = False
        self.stripe: Optional[RailGroup] = None
        self.stripe_rx: Optional[StripeRx] = None
        # per-rail striped-chunk rx parser state
        self._sdata: Optional[tuple] = None   # (tag, subhdr buf, got, blen)
        self._rx_stripe: Optional[tuple] = None  # (asm, offset, chunk_len)
        self._rx_stripe_got = 0
        # Receiver-driven flow control (DESIGN.md §18; negotiated via the
        # "fc" handshake key).  Sender half: ``fc_window`` is the PEER's
        # advertised unexpected-queue budget, ``fc_credits`` the signed
        # remainder (negative only via the one-oversized-frame
        # admission), ``fc_waiting`` the unframed FIFO of parked sends,
        # ``fc_rts`` the announced-but-unSACKed rendezvous sends
        # (msg_id -> [TxData, state, tag]; payload pinned until SACK).
        # Receiver half: ``fc_unexp`` is this conn's outstanding
        # (un-granted) spill bytes, ``fc_rx_gen`` the incarnation
        # generation that orphans stale grants across a session resume,
        # ``fc_rx`` the un-completed inbound RTS records (dedup for
        # re-announcements).  All zero/empty on seed-parity conns.
        self.fc_ok = False
        self.fc_window = 0
        self.fc_credits = 0
        self.fc_waiting: deque = deque()
        self.fc_rts: dict = {}
        self._fc_next_msg = 1
        self.fc_unexp = 0
        self.fc_rx_gen = 0
        self.fc_rx: dict = {}
        self._unexp_cap = config.unexp_cap()
        # §19 integrity plane (negotiated via the "csum" handshake key).
        # ``csum_ok`` arms TX framing + RX verification; ``poison_reason``
        # overrides the cancel reason at teardown ("corrupt");
        # ``_csum_pend`` is the (crc_frame, crc_head) pair announced by
        # the last T_CSUM with ``_csum_accum`` the running CRC of the
        # protected frame; ``retx_offs`` tracks NACK-requeued striped
        # chunks until rewritten (the ``retx_pending`` gauge).
        self.csum_ok = False
        self.poison_reason = None
        self._csum_pend = None
        self._csum_accum = 0
        self.retx_offs: set = set()
        self.sess = None
        self._sess_pending = None   # seq announced by the last T_SEQ
        self._sess_drop = False     # next frame is a duplicate: drain + drop
        self._rx_skip = 0           # dup-frame payload bytes left to drain
        self._sess_ack_armed = False  # idle ACK timer outstanding
        self.sess_fail_reason = None  # flush-failure override at expiry
        if mode == "socket":
            try:
                self.local_addr, self.local_port = sock.getsockname()[:2]
                self.remote_addr, self.remote_port = sock.getpeername()[:2]
            except OSError:
                pass
        # In address mode the endpoint reports empty socket fields, mirroring
        # the reference (README.md:141-143).

    # ------------------------------------------------------------------ sm
    def adopt_sm(self, seg, creator: bool, defer_tx: bool = False) -> None:
        """Switch this conn's framed stream onto shared-memory rings.

        Called on the connector after HELLO_ACK confirms ``sm: ok`` and on
        the acceptor before queueing that ACK (``defer_tx=True``: the ACK
        itself must still go over the socket, so TX moves to the ring only
        once the tx queue drains -- see kick_tx).  RX moves immediately:
        the peer writes no stream bytes to the socket past its own switch
        point.
        """
        self._sm = seg
        self.sm_tx, self.sm_rx = seg.tx_rx(creator)
        self.sm_active = True
        self.sm_negotiated = True
        seg.unlink()
        if not defer_tx:
            if self.tx:
                # Anything already queued predates the switch: it drains to
                # the socket, then TX flips (kick_tx sees the marker).
                self.tx[-1].switch_after = True
            else:
                self._tx_via_ring = True

    def _doorbell(self, fires: list, val: int = DB_DATA) -> None:
        b = bytes([val])
        if self._db_out:
            if val not in self._db_out:
                self._db_out.extend(b)
            return
        self._ctr.io_syscalls += 1  # §23 runtime cost twin
        try:
            self.sock.send(b)
        except BlockingIOError:
            # Queue + EPOLLOUT: the peer will drain the socket eventually and
            # the byte goes out then (never lost, never polled for).
            self._db_out.extend(b)
            self._sync_write_interest()
        except OSError:
            self.worker._conn_broken(self, fires)

    def on_writable(self, fires: list) -> None:
        """EPOLLOUT: flush queued doorbell bytes first, then the tx queue."""
        while self._db_out:
            self._ctr.io_syscalls += 1  # §23 runtime cost twin
            try:
                n = self.sock.send(self._db_out)
            except BlockingIOError:
                return
            except OSError:
                self.worker._conn_broken(self, fires)
                return
            del self._db_out[:n]
        self.kick_tx(fires)

    def _close_sm(self) -> None:
        if self._sm is not None:
            seg, self._sm = self._sm, None
            self.sm_tx = self.sm_rx = None
            # sm_negotiated stays set: introspection on dead endpoints still
            # reports what the conn ran on (same as the native engine).
            self.sm_active = False
            self._tx_via_ring = False
            seg.unlink()
            seg.close()

    # ------------------------------------------------------------------ tx
    def _tx_write(self, chunk) -> int:
        """Write bytes to the active transport; raises BlockingIOError when
        it cannot take any (socket buffer / ring full)."""
        t0 = time.perf_counter()
        if not self._tx_via_ring:
            self._ctr.io_syscalls += 1  # §23 runtime cost twin
            n = self.sock.send(chunk)
            if n:
                self._ctr.bytes_tx += n
                perf.record_stage("tx", time.perf_counter() - t0, n,
                                  self._scope)
            return n
        n = self.sm_tx.write(chunk)
        if n == 0:
            # Ring full.  kick_tx signals the peer with a starving doorbell;
            # its reply (sent after it drains) re-enters kick_tx.  All wakeup
            # signaling rides the socket, so syscall ordering makes the sleep
            # race-free even though pure Python cannot fence (shmring.py).
            raise BlockingIOError
        self._ctr.bytes_tx += n
        self._ctr.hot_copies += 1  # §23 sm ring put (one slot copy)
        perf.record_stage("tx", time.perf_counter() - t0, n, self._scope)
        return n

    def _tx_writev(self, views: list) -> int:
        """Gathered write of several views via :meth:`_tx_write` (the
        socket transport instead gathers across whole queue items in
        kick_tx's sendmsg pump); raises BlockingIOError when the transport
        cannot take any bytes."""
        total = 0
        for v in views:
            try:
                n = self._tx_write(v)
            except BlockingIOError:
                if total == 0:
                    raise
                break
            total += n
            if n < len(v):
                break
        return total

    # ---------------------------------------------------------- integrity
    def _csum_arm(self, item) -> None:
        """Embed the T_CSUM prefix into one tx item's framed bytes
        (DESIGN.md §19).  Runs at dispatch, after the item's final wire
        header exists and BEFORE any session T_SEQ framing, so the wire
        order is [SEQ][CSUM][frame] and journal replays stay
        byte-identical.  Handshake frames are never wrapped."""
        if not self.csum_ok:
            return
        if isinstance(item, (TxCtl, TxDevpull)):
            if item.data[0] in _CSUM_EXEMPT:
                return
            item.data = frames.pack_csum_for(item.data) + item.data
            return
        # TxData: flat host payload (device.py stages integrity conns
        # flat, like session conns -- the CRC needs the whole payload).
        payload = item.payload if isinstance(item.payload, memoryview) \
            else None
        item.header = frames.pack_csum_for(item.header, payload) \
            + item.header

    def _corrupt(self, fires: list, what: str) -> None:
        """Unrepairable verification failure: poison the conn with the
        stable "corrupt" reason.  Without a session this takes the §10
        failure contract (queued sends fail "corrupt", posted recvs keep
        the peer-death pendings, flush fails); with a live session
        _conn_broken suspends instead and the journal replay re-delivers
        verified bytes exactly-once."""
        self._ctr.csum_fail += 1
        logger.warning("starway: integrity failure on conn %s: %s",
                       self.conn_id, what)
        self.poison_reason = REASON_CORRUPT
        sess = self.sess
        if sess is None or sess.expired:
            # Flush barriers against the poisoned conn report the true
            # cause (engine.py _try_complete_flush reads this override).
            self.sess_fail_reason = REASON_CORRUPT
        self.worker._conn_broken(self, fires)

    def _on_snack(self, msg_id: int, off: int, fires: list) -> None:
        """The receiver NACKed one striped chunk (payload checksum failed
        with an intact sub-header): re-queue JUST that chunk.  The payload
        is pinned until T_SACK, so the resend is always legal; the
        receiver's offset dedup never recorded the corrupt chunk, so the
        retransmit streams into the same sink region."""
        if self.fc_ok and msg_id in self.fc_rts:
            # §18 rendezvous delivery (one self-describing chunk): the
            # whole frame rides again, exactly like a CTS re-dispatch.
            ent = self.fc_rts[msg_id]
            if ent[1] != "tx":
                return  # not dispatched yet (stale/garbled NACK)
            item = ent[0]
            if item in self.tx:
                return  # still (re)transmitting
            item.reset_for_replay()
            self._ctr.chunk_retx += 1
            self.tx.append(item)
            self.kick_tx(fires)
            return
        root = self.stripe_root()
        grp = root.stripe
        if grp is None:
            return
        src = grp.by_id.get(msg_id)
        if (src is None or src.sacked or src.failed
                or off >= src.total or off % src.chunk):
            return  # settled or garbled: a late SACK/redispatch covers it
        if off in src.pending:
            return  # duplicate NACK: already queued for resend
        for offs in src.rail_offs.values():
            if off in offs:
                return  # already back in flight on some lane
        removed = False
        for offs in src.done_offs.values():
            if off in offs:
                offs.remove(off)
                removed = True
                break
        if not removed:
            return  # ledger cleared by a resume: redispatch_all covers it
        src.pending.append(off)
        src.unwritten += 1
        root._ctr.chunk_retx += 1
        root.retx_offs.add((msg_id, off))
        if src not in grp.queue:
            grp.queue.append(src)
        grp.dispatch(fires)

    # ------------------------------------------------------------- stripe
    def stripe_root(self) -> "TcpConn":
        return self.rail_parent if self.rail_parent is not None else self

    def stripe_group(self) -> RailGroup:
        if self.stripe is None:
            self.stripe = RailGroup(self)
        return self.stripe

    def _stripe_rx_tbl(self) -> StripeRx:
        root = self.stripe_root()
        if root.stripe_rx is None:
            root.stripe_rx = StripeRx(root)
        return root.stripe_rx

    def attach_rail(self, conn: "TcpConn", fires: list) -> None:
        """Adopt ``conn`` as a secondary lane of this (primary) conn."""
        conn.rail_parent = self
        self.rails = [r for r in self.rails if r.alive]
        self.rails.append(conn)
        grp = self.stripe_group()
        grp.lanes = [ln for ln in grp.lanes
                     if ln.conn is self or ln.alive]
        grp.add_rail(conn)
        if grp.queue:
            grp.dispatch(fires)  # mid-stripe join: start stealing now

    def send_data(self, tag: int, payload, done, fail, owner, fires: list,
                  kick: bool = True):
        """Queue a tagged message.  Returns the TxData handle so the worker
        can arm a deadline timer against it (core/engine.py), or None when
        the conn is already dead.

        ``kick=False`` defers the transport push: the engine's op drain
        queues a whole burst of sends first and kicks each conn once, so
        the gathered pump coalesces the burst into single sendmsg passes
        (Worker._drain_ops)."""
        if not self.alive:
            if fail is not None:
                fires.append(lambda: fail(REASON_NOT_CONNECTED + " (connection reset)"))
            return None
        if self.rails:
            grp = self.stripe_group()
            nbytes = (len(payload) if isinstance(payload, memoryview)
                      else int(payload.nbytes))
            if grp.stripe_ok(nbytes, payload):
                # Striped path (DESIGN.md §17): the source is NOT
                # seq-framed even on session conns -- chunks are
                # idempotent and the journal is per-message (the group
                # re-dispatches un-SACKed sources wholesale at resume).
                # Striped sends are exempt from the §18 credit window:
                # like the RTS path they are SACK-terminated large
                # transfers (stripe_threshold should sit at or above the
                # rndv threshold when combining the two planes).
                return grp.submit(tag, payload, done, fail, owner, fires)
        if self.fc_ok:
            return self._fc_send(tag, payload, done, fail, owner, fires, kick)
        self.dirty = True
        self._data_counter += 1
        item = TxData(tag, payload, done, fail, owner, self._hists)
        self._csum_arm(item)
        if self.sess is not None:
            self._sess_submit(item, fires, kick)
            return item
        self.tx.append(item)
        if kick:
            self.kick_tx(fires)
        return item

    def _proto_tx(self, ftype: int) -> None:
        """swrefine tx event at the ctl-plane handoff (DESIGN.md §22;
        data frames are covered by send_post/send_done and the peer's
        rx events)."""
        self._proto.rec(swtrace.EV_PROTO, 0, self.conn_id, 0,
                        "tx:" + frames.FRAME_NAMES.get(ftype, "OTHER"))

    def send_flush(self, seq: int, fires: list) -> None:
        self._flush_marks[seq] = self._data_counter
        if self._proto is not None:
            self._proto_tx(frames.T_FLUSH)
        item = TxCtl(frames.pack_flush(seq))
        self._csum_arm(item)
        if self.sess is not None:
            self._sess_submit(item, fires, True)
            return
        self.tx.append(item)
        self.kick_tx(fires)

    def send_flush_ack(self, seq: int, fires: list) -> None:
        """FLUSH_ACK is a *sequenced* session frame (a barrier ACK lost
        with a conn must replay, or the peer's flush hangs forever)."""
        if self._proto is not None:
            self._proto_tx(frames.T_FLUSH_ACK)
        item = TxCtl(frames.pack_flush_ack(seq))
        self._csum_arm(item)
        if self.sess is not None:
            self._sess_submit(item, fires, True)
            return
        self.tx.append(item)
        self.kick_tx(fires)

    def on_flush_acked(self, seq: int) -> None:
        mark = self._flush_marks.pop(seq, None)
        if mark is not None and mark == self._data_counter:
            self.dirty = False

    def send_ctl(self, data: bytes, fires: list, switch_after: bool = False) -> None:
        if self._proto is not None and data:
            self._proto_tx(data[0])  # the frame header leads with its type
        item = TxCtl(data, switch_after)
        self._csum_arm(item)
        self.tx.append(item)
        self.kick_tx(fires)

    def send_ping(self, fires: list) -> None:
        """Liveness probe (only sent on ka-negotiated conns).  Rides the
        active transport -- ring for sm conns (the doorbell accompanies it
        via kick_tx), socket otherwise.  Always timestamped: the PONG then
        doubles as a swscope clock sample (old peers echo zeros)."""
        if self.alive:
            self.send_ctl(frames.pack_ping(time.perf_counter_ns()), fires)

    # ------------------------------------------------------------ swscope
    def _tx_e2e(self, item) -> None:
        """One EV_E2E per data frame, at its FIRST full handoff to the
        transport -- completion order IS wire order, so the ordinal here
        equals the receiver's accept ordinal for the same message
        (DESIGN.md §15).  The ``counted`` guard on the call sites makes
        this once-only across session replays."""
        if self._ring is None or not self.tr_id:
            return
        self.tx_e2e_ord += 1
        item.e2e_ord = self.tx_e2e_ord
        nbytes = getattr(item, "nbytes", None)
        if nbytes is None:
            nbytes = len(item.data)
        self._ring.rec(swtrace.EV_E2E, self.tx_e2e_ord, self.conn_id,
                       nbytes, self.tr_id + ":tx")

    def _rx_e2e(self, nbytes: int) -> None:
        """Receiver half of the pair: one EV_E2E per accepted (non-dup)
        data frame, in stream order.  Dup session frames drain via
        ``_sess_drop``/``_rx_skip`` and never reach this counter."""
        if self._ring is None or not self.tr_id:
            return
        self.rx_e2e_ord += 1
        self._ring.rec(swtrace.EV_E2E, self.rx_e2e_ord, self.conn_id,
                       nbytes, self.tr_id + ":rx")

    def _on_pong(self, echo_ns: int, peer_ns: int) -> None:
        """A timestamped PONG closed the loop: one NTP-style clock sample
        for this peer -- ``offset = t_peer - (t_tx + rtt/2)``, error
        ``rtt/2``.  Zero fields mean an old peer's plain probe answer."""
        if not echo_ns or not peer_ns:
            return
        now = time.perf_counter_ns()
        rtt = now - echo_ns
        if rtt < 0:
            return  # a replayed/garbled echo cannot yield a sane sample
        err_us = max(1, rtt // 2000)
        off_us = (peer_ns - (echo_ns + rtt // 2)) // 1000
        if self.clock_err_us == 0 or err_us < self.clock_err_us:
            self.clock_off_us = off_us
            self.clock_err_us = err_us
        if self._ring is not None and self.tr_id:
            self._ring.rec(swtrace.EV_CLOCK, 0, self.conn_id, 0,
                           f"{self.tr_id}:{off_us}:{err_us}")

    def send_devpull(self, data: bytes, done, fail, owner, fires: list,
                     kick: bool = True) -> None:
        """Queue a DEVPULL descriptor (counts as data for flush/dirty
        accounting: the flush barrier must cover the pulled payload)."""
        if not self.alive:
            if fail is not None:
                fires.append(lambda: fail(REASON_NOT_CONNECTED + " (connection reset)"))
            return
        self.dirty = True
        self._data_counter += 1
        if self._proto is not None:
            self._proto_tx(frames.T_DEVPULL)
        item = TxDevpull(data, done, fail, owner)
        self._csum_arm(item)
        if self.sess is not None:
            self._sess_submit(item, fires, kick)
            return
        self.tx.append(item)
        if kick:
            self.kick_tx(fires)

    # ------------------------------------------------------------- session
    @staticmethod
    def _sess_wire_bytes(item) -> int:
        """Wire footprint of an unframed item (payload + frame header +
        the T_SEQ prefix it will gain)."""
        base = item.total if isinstance(item, TxData) else len(item.data)
        return base + frames.HEADER_SIZE

    def _sess_frame(self, item) -> None:
        seq = self.sess.next_seq()
        item.sess_wrap(seq, frames.pack_seq(seq))
        self.sess.journal_add(item, item.sess_nbytes)

    def _sess_submit(self, item, fires: list, kick: bool) -> None:
        """Frame + journal + queue a session frame, or park it when the
        journal is at its byte cap (backpressure: the send completes late
        instead of the journal OOMing).  Parked items keep FIFO order."""
        sess = self.sess
        if not sess.has_room(self._sess_wire_bytes(item)):
            sess.waiting.append(item)
            return
        self._sess_frame(item)
        self.tx.append(item)
        if kick:
            self.kick_tx(fires)

    def _sess_drain_waiting(self) -> bool:
        """Move parked items into the journal/tx as ACKs free room.
        Returns True when anything moved (caller kicks)."""
        sess = self.sess
        moved = False
        while sess.waiting:
            item = sess.waiting[0]
            nb = self._sess_wire_bytes(item)
            if sess.journal and sess.journal_bytes + nb > sess.journal_cap:
                break
            sess.waiting.popleft()
            self._sess_frame(item)
            self.tx.append(item)
            moved = True
        return moved

    def _on_ack(self, cum_seq: int, fires: list) -> None:
        """Peer's cumulative ACK: trim the journal, unblock parked sends."""
        self._ctr.acks_rx += 1
        self.sess.journal_trim(cum_seq)
        if self._sess_drain_waiting():
            self.kick_tx(fires)

    def _on_seq(self, seq: int, fires: list) -> bool:
        """T_SEQ announcing the next frame's sequence number.  Returns
        False when the conn was torn down (seq gap)."""
        sess = self.sess
        if sess is None:
            # Peer speaks the session protocol on a conn that never
            # negotiated it: protocol violation.
            self.worker._conn_broken(self, fires)
            return False
        if seq <= sess.rx_cum:
            # Already processed (a replay overlap): drain + drop the frame.
            self._ctr.dup_frames_dropped += 1
            self._sess_drop = True
        elif seq == sess.rx_cum + 1:
            self._sess_pending = seq
        else:
            # Gap inside one incarnation (reordered/corrupted relay): the
            # framed stream cannot be repaired in place -- reset and let
            # the resume handshake replay from the cumulative ACK.
            self.worker._conn_broken(self, fires)
            return False
        return True

    def _sess_commit(self) -> None:
        """The sequenced frame announced by the last T_SEQ was fully
        processed: advance the cumulative counter and make sure an ACK
        eventually goes out even if no further reads piggyback one."""
        if self._sess_pending is None:
            return
        self.sess.rx_cum = self._sess_pending
        self._sess_pending = None
        if not self._sess_ack_armed:
            self._sess_ack_armed = True
            self.worker._add_timer(0.2, self._sess_ack_tick)

    def _sess_ack_tick(self, fires: list) -> None:
        self._sess_ack_armed = False
        self._sess_maybe_ack(fires)

    def _sess_maybe_ack(self, fires: list) -> None:
        """Piggybacked cumulative ACK: sent at the end of a read pass (and
        from the idle timer) whenever rx progress is unacknowledged."""
        sess = self.sess
        if sess is None or not self.alive or sess.suspended:
            return
        if sess.rx_cum > sess.acked_sent:
            sess.acked_sent = sess.rx_cum
            self._ctr.acks_tx += 1
            self.send_ctl(frames.pack_ack(sess.acked_sent), fires)

    def suspend(self, fires: list) -> None:
        """The transport died but the session is resumable: drop the
        socket and all per-incarnation parser state, keep every queue,
        journal, and flush bookkeeping.  The conn stays ``alive`` so
        flush barriers keep waiting and new sends keep queueing -- they
        complete after resume instead of failing."""
        if self._proto is not None:
            # swrefine: (estab, lost) -> suspended (DESIGN.md §22).
            self._proto.rec(swtrace.EV_PROTO, 0, self.conn_id, 0, "lost")
        sess = self.sess
        sess.suspend()
        self.worker._unregister_conn_io(self)
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        # rx parser reset: the replayed stream restarts at a frame boundary.
        self._hdr_got = 0
        self._ctl = None
        self._rx_skip = 0
        self._sess_drop = False
        self._sess_pending = None
        self._csum_pend = None  # per-incarnation: replay re-announces
        self._csum_accum = 0
        # Striped rx parser state is per-incarnation; the ASSEMBLIES
        # (stripe_rx) survive -- the resumed sender re-dispatches
        # un-SACKed sources and offset dedup keeps bytes exactly-once.
        self._sdata = None
        self._rx_stripe = None
        self._rx_stripe_got = 0
        msg, self._rx_msg = self._rx_msg, None
        if msg is not None:
            with self.worker.lock:
                pr = msg.posted
                if pr is not None and not msg.complete:
                    # Re-arm the stranded receive at the FRONT of the
                    # queue: the replayed frame must claim the same
                    # receive (its buffer was partially written; the
                    # replay rewrites it from the start).
                    msg.posted = None
                    pr.claimed = False
                    self.worker.matcher.purge_inflight(msg)
                    self.worker.matcher.posted.appendleft(pr)
                else:
                    self.worker.matcher.purge_inflight(msg)
        # Journaled frames replay from the journal; bare per-incarnation
        # ctl (PING/PONG/ACK) queued on the old transport dies with it.
        self.tx.clear()
        self._db_out = bytearray()
        self._want_write = False
        self._tx_want_sock = False

    def resume(self, sock: socket.socket, peer_ack: int, fires: list,
               ack_ctl: Optional[bytes] = None) -> None:
        """A reconnect re-handshake matched this session: adopt the new
        socket, trim the journal by the peer's cumulative ACK (carried in
        the handshake), and replay everything past it.  ``ack_ctl`` is the
        acceptor's HELLO_ACK -- it must precede replayed frames on the
        wire."""
        if self._proto is not None:
            # swrefine: (suspended, resume) -> estab; the resume dial's
            # HELLO/HELLO_ACK exchange is folded into this one event
            # (the conn never leaves the session machine, DESIGN.md §22).
            self._proto.rec(swtrace.EV_PROTO, 0, self.conn_id, 0, "resume")
        sess = self.sess
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.sock = sock
        self.last_rx = time.monotonic()
        sess.resume()
        sess.journal_trim(peer_ack)
        # The handshake carried our rx_cum as sess_ack: the peer starts
        # from it, so there is nothing older to re-ACK.
        sess.acked_sent = sess.rx_cum
        # Frames queued while suspended are all journaled (submit framing
        # happens at queue time): rebuild tx purely from the journal, or
        # those items would ride the wire twice.
        self.tx.clear()
        self._ctr.sessions_resumed += 1
        if ack_ctl is not None:
            self.tx.append(TxCtl(ack_ctl))
        replayed = 0
        for item in sess.journal:
            item.reset_for_replay()
            self.tx.append(item)
            replayed += 1
            if not isinstance(item, TxCtl) and item.counted \
                    and item.e2e_ord and self._ring is not None \
                    and self.tr_id:
                # swscope: this frame's ordinal was already recorded at
                # its first full transmission; the replay rewrites the
                # bytes (the receiver's seq dedup drops them if they
                # landed) -- mark it superseded, never recount it.
                self._ring.rec(swtrace.EV_E2E, item.e2e_ord, self.conn_id,
                               0, self.tr_id + ":sup")
        self._ctr.frames_replayed += replayed
        self._sess_drain_waiting()  # trim may have freed journal room
        if self.fc_ok:
            # Fresh credit window per incarnation; unSACKed rendezvous
            # sends re-announce; parked sends re-enter dispatch
            # (DESIGN.md §18 -- the journal already owns their bytes).
            self._fc_reset_resume()
            self._fc_drain_waiting(fires)
        if self.stripe is not None:
            # Un-SACKed striped sources re-dispatch wholesale (chunk 0
            # onward) across whatever lanes are live -- the per-message
            # journal contract; rails re-attach as the client re-dials.
            self.stripe.lanes = [ln for ln in self.stripe.lanes
                                 if ln.conn is self or ln.alive]
            self.rails = [r for r in self.rails if r.alive]
            self.stripe.redispatch_all(fires)
        tr = getattr(self.worker, "_trace", None)
        if tr is not None:
            tr.rec(swtrace.EV_SESS_RESUME, 0, self.conn_id, replayed)
        swtrace.flight_dump("session-resume", self.worker)
        self.worker._register_conn_io(self)
        self.kick_tx(fires)

    # -------------------------------------------------------- flow control
    #
    # Receiver-driven credit flow control + the RTS/CTS rendezvous path
    # (DESIGN.md §18; negotiated via the "fc" handshake key).  Sender
    # half below runs on the engine thread (send_data routes through it);
    # the receiver half hangs off _pump_frames and the matcher's
    # fc_release hook.

    def _fc_send(self, tag: int, payload, done, fail, owner, fires: list,
                 kick: bool):
        """send_data on an fc conn: gate eager sends on the peer's
        window, announce rendezvous sends via RTS.  Once anything is
        parked, EVERYTHING parks behind it -- FIFO arrival order at the
        receiver's matcher is part of the matching contract."""
        item = TxData(tag, payload, done, fail, owner, self._hists)
        if self.fc_waiting:
            item.t_park = time.perf_counter()
            self.fc_waiting.append(item)
            self._ctr.sends_parked += 1
            return item
        if item.rndv:
            self._fc_rts_announce(item, fires, kick)
            return item
        if not self._fc_admit(item.nbytes):
            item.t_park = time.perf_counter()
            self.fc_waiting.append(item)
            self._ctr.sends_parked += 1
            return item
        self._fc_dispatch_eager(item, fires, kick)
        return item

    def _fc_admit(self, nbytes: int) -> bool:
        """Debit the window, or refuse.  A fully-replenished (idle)
        window always admits one frame even when the payload exceeds it
        -- the §14 journal-backpressure rule: a single oversized payload
        must block later sends, never deadlock itself."""
        if self.fc_credits >= nbytes or self.fc_credits >= self.fc_window:
            self.fc_credits -= nbytes
            return True
        return False

    def _fc_dispatch_eager(self, item, fires: list, kick: bool) -> None:
        self.dirty = True
        self._data_counter += 1
        self._csum_arm(item)
        if self.sess is not None:
            self._sess_submit(item, fires, kick)
            return
        self.tx.append(item)
        if kick:
            self.kick_tx(fires)

    def _fc_rts_announce(self, item, fires: list, kick: bool) -> None:
        """Announce a rendezvous send: the payload stays pinned here and
        travels as ONE self-describing T_SDATA frame only after the
        receiver's CTS -- large transfers never consume window and never
        spill at the receiver.  The RTS ctl is per-incarnation (never
        seq-framed): a session resume re-announces every unSACKed entry
        instead of replaying it."""
        self.dirty = True
        self._data_counter += 1
        msg_id = frames.FC_MSG_BIT | self._fc_next_msg
        self._fc_next_msg += 1
        item.header = frames.pack_sdata_header(item.tag, msg_id, 0,
                                               item.nbytes, item.nbytes)
        self._csum_arm(item)  # covers header+sub-header+payload (§19)
        self.fc_rts[msg_id] = [item, "rts", item.tag]
        rts = TxCtl(frames.pack_rts(item.tag, msg_id, item.nbytes))
        self._csum_arm(rts)
        self.tx.append(rts)
        if kick:
            self.kick_tx(fires)

    def _on_credit(self, nbytes: int, fires: list) -> None:
        """Peer returned window (T_CREDIT): replenish and drain parked
        sends.  Clamped at the advertised window -- a wire-duplicated
        grant must never mint credit."""
        if not self.fc_ok:
            return  # stray grant on a non-fc conn: old peers cannot send it
        self.fc_credits = min(self.fc_window, self.fc_credits + nbytes)
        self._fc_drain_waiting(fires)

    def _fc_drain_waiting(self, fires: list) -> None:
        """Move parked sends into dispatch as grants restore the window
        (FIFO; rendezvous entries pass straight through to RTS)."""
        moved = False
        while self.fc_waiting:
            item = self.fc_waiting[0]
            if item.local_done:  # shed by a deadline while parked
                self.fc_waiting.popleft()
                item._pulse_unpark()
                continue
            if item.rndv:
                self.fc_waiting.popleft()
                item._pulse_unpark()
                self._fc_rts_announce(item, fires, kick=False)
                moved = True
                continue
            if not self._fc_admit(item.nbytes):
                break
            self.fc_waiting.popleft()
            item._pulse_unpark()
            self._fc_dispatch_eager(item, fires, kick=False)
            moved = True
        if moved:
            self.kick_tx(fires)

    def _on_cts(self, msg_id: int, fires: list) -> None:
        """Receiver granted the rendezvous: dispatch the pinned payload
        as its pre-built T_SDATA frame.  A duplicate CTS (resume races)
        is ignored -- only the "rts" state dispatches."""
        ent = self.fc_rts.get(msg_id)
        if ent is None or ent[1] != "rts":
            return
        ent[1] = "tx"
        item = ent[0]
        item.reset_for_replay()
        self.tx.append(item)
        self.kick_tx(fires)

    def _fc_on_sack(self, msg_id: int, fires: list) -> bool:
        """True when this SACK settled a §18 rendezvous send (the entry
        -- and with it the payload pin -- is dropped; the op completed
        locally at first byte, rndv semantics)."""
        return self.fc_rts.pop(msg_id, None) is not None

    def fc_rts_state(self, item):
        """The fc_rts state ("rts"/"tx") owning ``item``, or None --
        the deadline path's promised-send probe (core/engine.py)."""
        for ent in self.fc_rts.values():
            if ent[0] is item:
                return ent[1]
        return None

    def _fc_reset_resume(self) -> None:
        """Fresh window per incarnation (DESIGN.md §18): stale debits and
        grant obligations die with the old transport.  Journal-replayed
        DATA frames re-debit the fresh window (their replay WILL arrive,
        and the receiver grants duplicates too -- conservation), parked
        sends re-enter dispatch, and unSACKed rendezvous sends
        re-announce (the receiver's assembly/done-LRU dedups)."""
        self.fc_rx_gen += 1
        self.fc_unexp = 0
        self.fc_credits = self.fc_window
        if self.sess is not None:
            # Journal-replayed frames AND journal-backpressure-parked
            # frames (sess.waiting) both ship in this incarnation and
            # were admitted pre-suspend: re-debit both, or their wire
            # bytes would oversubscribe the fresh window.
            for it in list(self.sess.journal) + list(self.sess.waiting):
                if isinstance(it, TxData):
                    self.fc_credits -= it.nbytes
        for msg_id, ent in self.fc_rts.items():
            ent[1] = "rts"
            ent[0].reset_for_replay()
            rts = TxCtl(frames.pack_rts(ent[2], msg_id, ent[0].nbytes))
            self._csum_arm(rts)
            self.tx.append(rts)

    # --------------------------------------------------- flow control (rx)
    def fc_on_rts(self, tag: int, msg_id: int, total: int, fires: list) -> None:
        """An RTS descriptor arrived: register the rendezvous offer with
        the matcher through the devpull machinery (flush deferral,
        truncation drain, and force-start come with it); CTS goes out
        when a receive claims the record."""
        rx = self._stripe_rx_tbl()
        if msg_id in rx.done_ids:
            # Late re-announcement of a completed message: re-SACK so the
            # sender releases its pin.
            StripeRx.sack(self, msg_id, total, fires)
            return
        msg = self.fc_rx.get(msg_id)
        if msg is not None:
            if msg_id in rx.asms:
                # The CTS (or the delivery) died with an incarnation; the
                # assembly survived -- just re-CTS.
                self.send_ctl(frames.pack_cts(msg_id), fires)
            elif (msg.remote is not None
                  and (msg.posted is not None or msg.discard
                       or msg.remote.started)):
                # The CTS hop was consumed by a dead incarnation AFTER a
                # claim (or drain) consumed the record: no future
                # post_recv can re-fire it -- restart on the live conn
                # (fc_start_rx dedups against a stale queued hop via the
                # assembly table).
                msg.remote.started = True
                self.fc_start_rx(msg, fires)
            return
        handle = RtsHandle(self, msg_id, total, tag)
        with self.worker.lock:
            msg, f = self.worker.matcher.on_remote_message(tag, total, handle)
        fires.extend(f)
        handle.msg = msg
        self.fc_rx[msg_id] = msg
        self.remote_received(msg)
        if msg.discard:
            # Matched a too-small receive at announce time: the receive
            # already failed "truncated", but the sender still pins the
            # payload -- drain-CTS it so the pin (and any flush barrier)
            # releases, exactly like a truncated devpull descriptor.
            fires.append(lambda m=msg: m.remote.start(m))

    def fc_start_rx(self, msg, fires: list) -> None:
        """Engine-thread half of the CTS (RtsHandle.start hops here):
        choose the sink, pre-register the assembly under the sender's
        msg id, answer CTS.  The T_SDATA delivery then streams through
        the ordinary stripe RX path."""
        handle = msg.remote
        if handle is None or msg.complete:
            return
        rx = self._stripe_rx_tbl()
        if handle.msg_id in rx.asms:
            return  # already registered (a duplicate/stale hop)
        if not self.alive or self.sock is None:
            # Dead/suspended: this hop is consumed, so re-arm the handle
            # -- the resume re-announcement restarts it (fc_on_rts).
            handle.started = False
            return
        handle.started = True
        if not msg.discard and msg.posted is None and msg.spill is None:
            # Force-started by a flush barrier before any receive
            # matched: spill, like a drained devpull (exempt from the
            # window -- the sender's flush asked for residency here).
            msg.spill = bytearray(msg.length)
            msg.sink = memoryview(msg.spill)
        elif msg.posted is not None and msg.sink is None:
            pr = msg.posted
            if isinstance(pr.buf, memoryview):
                msg.sink = pr.buf
            else:
                msg.sink = pr.buf.host_staging()
        from .lane import StripeAsm

        rx.asms[handle.msg_id] = StripeAsm(handle.msg_id, handle.tag,
                                           msg.length, msg)
        self.send_ctl(frames.pack_cts(handle.msg_id), fires)

    # ------------------------------------------------- devpull rx tracking
    def remote_received(self, msg) -> None:
        self._remote_msgs.add(msg)

    def defer_flush_ack(self, seq: int) -> None:
        """Hold this barrier's ACK until the descriptors that PRECEDED it in
        the stream resolve.  Snapshot, not the live set: a descriptor
        arriving after the barrier must not extend the wait."""
        self._deferred_flush_acks.append((seq, set(self._remote_msgs)))

    def remote_resolved(self, msg, fires: list) -> None:
        """A descriptor's pull completed/failed/was discarded: release any
        FLUSH_ACKs whose snapshot it was the last unresolved member of."""
        self._remote_msgs.discard(msg)
        if not self._deferred_flush_acks:
            return
        ready = []
        remaining = []
        for seq, waiting in self._deferred_flush_acks:
            waiting.discard(msg)
            (remaining if waiting else ready).append((seq, waiting))
        self._deferred_flush_acks = remaining
        if self.alive:
            for seq, _ in ready:
                self.send_flush_ack(seq, fires)

    def _gather_tx(self) -> tuple[list, list]:
        """Collect unwritten views across queued items for one sendmsg pass
        (the multi-item extension of the header+payload ``_tx_writev``;
        mirrors the native engine's tcp_tx_gather).  Returns (views,
        [(item, offered_bytes)]); never batches past the sm switch point."""
        views: list = []
        spans: list = []
        take = 0
        for item in self.tx:
            if len(views) >= GATHER_IOV or take >= TX_CHUNK:
                break
            offered = 0
            for v in item.tx_views(TX_CHUNK - take):
                views.append(v)
                offered += len(v)
            take += offered
            if offered:
                spans.append((item, offered))
            if isinstance(item, StripeFeeder):
                # A feeder refills in place after its chunk completes, so
                # the byte budget must never span past it (the native
                # pump's front-pop accounting has the same rule -- keep
                # the two in lockstep).
                break
            if item.switch_after:
                break
            if offered < item.remaining:
                # Item not fully offered (byte budget, or a chunked payload
                # whose later chunks are not staged yet): nothing behind it
                # may ride this pass, or the later frame's bytes would land
                # inside this item's in-flight DATA payload.
                break
        return views, spans

    def kick_tx(self, fires: list) -> None:
        if not self.alive or self.sock is None:
            return  # dead, or session-suspended (resume re-kicks)
        t0 = self.sm_tx.tail if self.sm_active else 0
        blocked = False
        try:
            while self.tx:
                if isinstance(self.tx[0], StripeFeeder) \
                        and self.tx[0].remaining == 0:
                    # A feeder that ran the group dry (remaining re-checks
                    # the claim) must leave the queue, or the gather pump
                    # -- which never batches past a feeder -- would stall
                    # every frame queued behind it.
                    self.tx.popleft()
                    continue
                if self._tx_via_ring:
                    item = self.tx[0]
                    if not item.write(self, fires):
                        blocked = True
                        break
                    self.tx.popleft()
                    if not isinstance(item, TxCtl) and not item.counted:
                        item.counted = True
                        self._ctr.sends_completed += 1
                        self._tx_e2e(item)
                    continue
                # Socket: one gathered sendmsg per pass across queued items
                # -- a burst of small frames costs one syscall, and a large
                # payload's next chunk rides along with whatever control
                # frames queued behind it.
                views, spans = self._gather_tx()
                if not views:
                    break
                tw0 = time.perf_counter()
                self._ctr.io_syscalls += 1  # §23 runtime cost twin
                try:
                    n = self.sock.sendmsg(views)
                except BlockingIOError:
                    first = self.tx[0]
                    if isinstance(first, TxData):
                        first._maybe_local_complete(fires)
                    blocked = True
                    break
                ctr = self._ctr
                ctr.bytes_tx += n
                ctr.gather_passes += 1
                ctr.gather_items += len(views)
                perf.record_stage("tx", time.perf_counter() - tw0, n,
                                  self._scope)
                for item, offered in spans:
                    adv = min(n, offered)
                    if adv == 0:
                        break
                    item.advance(adv, fires)
                    n -= adv
                    if item.remaining == 0 and self.tx and self.tx[0] is item:
                        self.tx.popleft()
                        if not isinstance(item, TxCtl) and not item.counted:
                            item.counted = True
                            ctr.sends_completed += 1
                            self._tx_e2e(item)
                        if getattr(item, "switch_after", False):
                            # The sm switch point (HELLO_ACK) left the
                            # socket: every later item rides the ring, even
                            # those already queued.  _gather_tx stopped at
                            # this item, so no later bytes were sent.
                            self._tx_via_ring = True
        except (BrokenPipeError, ConnectionResetError, OSError):
            self.worker._conn_broken(self, fires)
            return
        except Exception:
            # Chunked D2H staging failed mid-message (host_chunk raised:
            # the array was deleted/donated after asend, or a device
            # runtime error).  The frame header already promised nbytes the
            # stream can no longer produce, so reset the connection (the
            # same discipline as a deadline on a started send) -- queued
            # ops fail with the stable "cancel" reason instead of the
            # whole engine emergency-closing.
            logger.exception("starway: TX staging failed; resetting connection")
            self.worker._conn_broken(self, fires)
            return
        if blocked:
            self._set_want_write(True)
            if self._tx_via_ring:
                # Blocked on the ring, not the socket (EPOLLOUT would spin).
                # Ask the peer to reply once it drains; the starving byte
                # doubles as the data doorbell for anything published above.
                self._doorbell(fires, DB_STARVING)
                return
        else:
            self._set_want_write(False)
            if self.sm_active and not self._tx_via_ring:
                # Pre-switch TCP bytes (the HELLO_ACK) fully drained: all
                # stream traffic from here on rides the ring.
                self._tx_via_ring = True
        if self.sm_active and self.sm_tx.tail != t0:
            self._doorbell(fires)

    def _set_want_write(self, want: bool) -> None:
        # ``want`` tracks the tx queue's need for the socket.  A ring block
        # never wants EPOLLOUT (the socket stays writable; the wakeup is the
        # peer's doorbell reply); queued doorbell bytes always do.
        self._tx_want_sock = want and not self._tx_via_ring
        self._sync_write_interest()

    def _sync_write_interest(self) -> None:
        want = self._tx_want_sock or bool(self._db_out)
        if want != self._want_write:
            self._want_write = want
            self.worker._update_conn_interest(self)

    def has_unfinished_data_tx(self) -> bool:
        for it in self.tx:
            if isinstance(it, TxData) and it.off < it.total:
                return True
            if isinstance(it, StripeFeeder) \
                    and getattr(it, "src", None) is not None:
                return True
        return False

    # ------------------------------------------------------------------ rx
    def _rx_read(self, target) -> int:
        """Read stream bytes from the active transport into ``target``.

        Raises BlockingIOError when nothing is available; returns 0 only on
        TCP EOF (the ring has no EOF -- peer death surfaces on the socket).
        """
        t0 = time.perf_counter()
        if self.sm_active:
            try:
                n = self.sm_rx.read_into(target)
            except SmCorrupt as e:
                # §19: a torn/corrupt ring slot, caught at dequeue before
                # its bytes could be parsed.  Mark the poison here (this
                # helper has no fires list) and let the caller's OSError
                # handler run _conn_broken -- mark_dead then reports the
                # stable "corrupt" reason.
                self._ctr.csum_fail += 1
                logger.warning("starway: integrity failure on conn %s: %s",
                               self.conn_id, e)
                self.poison_reason = REASON_CORRUPT
                if self.sess is None or self.sess.expired:
                    self.sess_fail_reason = REASON_CORRUPT
                raise
            if n == 0:
                raise BlockingIOError
            self.last_rx = time.monotonic()
            self._ctr.bytes_rx += n
            self._ctr.hot_copies += 1  # §23 sm ring take (one slot copy)
            perf.record_stage("rx", time.perf_counter() - t0, n, self._scope)
            return n
        self._ctr.io_syscalls += 1  # §23 runtime cost twin
        n = self.sock.recv_into(target)
        if n:
            self.last_rx = time.monotonic()
            self._ctr.bytes_rx += n
            perf.record_stage("rx", time.perf_counter() - t0, n, self._scope)
        return n

    def on_readable(self, fires: list) -> None:
        if not self.sm_active:
            self._pump_frames(fires)
            self._sess_maybe_ack(fires)  # piggybacked cumulative ACK
            return
        # sm mode: the socket carries only doorbells (and EOF/RST).  Drain
        # it, then pump the ring.  On EOF the peer is gone, but bytes it
        # published before dying are still in the ring: pump first, then
        # declare the conn broken (graceful close must deliver).
        eof = False
        starving = False
        while True:
            self._ctr.io_syscalls += 1  # §23 runtime cost twin
            try:
                b = self.sock.recv(4096)
            except BlockingIOError:
                break
            except (ConnectionResetError, OSError):
                eof = True
                break
            if not b:
                eof = True
                break
            self.last_rx = time.monotonic()  # doorbell bytes are proof of life
            if DB_STARVING in b:
                starving = True
        self._pump_frames(fires)
        if not self.alive:
            return
        if starving:
            # The peer's producer is asleep on a full ring.  The pump above
            # freed space (or it was already free); reply unconditionally --
            # our send comes after the head store, so by the time the peer's
            # recv returns, its view of the cursors is current.
            self._doorbell(fires)
        if self.tx:
            self.kick_tx(fires)  # the doorbell may mean tx-ring space freed
        if eof and self.alive:
            self._pump_frames(fires)
            self.worker._conn_broken(self, fires)

    def _pump_frames(self, fires: list) -> None:
        matcher = self.worker.matcher
        lock = self.worker.lock
        while self.alive:
            if self._rx_skip:
                # Duplicate sequenced frame: drain its payload to scratch
                # without touching the matcher (exactly-once delivery).
                if self._scratch is None:
                    self._scratch = bytearray(RX_CHUNK)
                target = memoryview(self._scratch)[: min(self._rx_skip, RX_CHUNK)]
                try:
                    n = self._rx_read(target)
                except BlockingIOError:
                    return
                except (ConnectionResetError, OSError):
                    self.worker._conn_broken(self, fires)
                    return
                if n == 0:
                    self.worker._conn_broken(self, fires)
                    return
                if self._csum_pend is not None:
                    self._csum_accum = frames.crc32c(target[:n],
                                                     self._csum_accum)
                self._rx_skip -= n
                if self._rx_skip == 0 and self._csum_pend is not None:
                    # A drained frame (duplicate seq / superseded chunk)
                    # ends here: verify for accounting only -- nothing
                    # was delivered, so a mismatch needs no recovery.
                    pend, self._csum_pend = self._csum_pend, None
                    if self._csum_accum != pend[0]:
                        self._ctr.csum_fail += 1
                continue
            if self._sdata is not None:
                # Striped-chunk sub-header (24 bytes: msg id, offset,
                # total) accumulating on this rail.
                stag, sub, got, blen = self._sdata
                try:
                    n = self._rx_read(memoryview(sub)[got:])
                except BlockingIOError:
                    return
                except (ConnectionResetError, OSError):
                    self.worker._conn_broken(self, fires)
                    return
                if n == 0:
                    self.worker._conn_broken(self, fires)
                    return
                if self._csum_pend is not None:
                    self._csum_accum = frames.crc32c(
                        memoryview(sub)[got:got + n], self._csum_accum)
                got += n
                if got < len(sub):
                    self._sdata = (stag, sub, got, blen)
                    continue
                self._sdata = None
                if (self._csum_pend is not None
                        and self._csum_accum != self._csum_pend[1]):
                    # Routing fields (header+sub-header) cannot be
                    # trusted: the stream framing itself is suspect, and
                    # a NACK would carry garbage ids -- poison instead.
                    self._corrupt(fires, "stripe sub-header checksum")
                    return
                msg_id, off, total = frames.SDATA_SUB.unpack(sub)
                chunk_len = blen - frames.SDATA_SUB_SIZE
                rx = self._stripe_rx_tbl()
                asm = rx.chunk_start(stag, msg_id, off, total, chunk_len,
                                     fires)
                if asm is None:
                    # Duplicate offset or already-completed message
                    # (rail-death resend / session replay): drain the
                    # chunk, re-SACK completed ids so the sender stops.
                    self._rx_skip = chunk_len
                    if msg_id in rx.done_ids:
                        rx.sack(self, msg_id, total, fires)
                    continue
                self._rx_stripe = (asm, off, chunk_len)
                self._rx_stripe_got = 0
                continue
            if self._rx_stripe is not None:
                asm, off, clen = self._rx_stripe
                got = self._rx_stripe_got
                remaining = clen - got
                m = asm.msg
                if m.discard or m.sink is None:
                    if self._scratch is None:
                        self._scratch = bytearray(RX_CHUNK)
                    target = memoryview(self._scratch)[: min(remaining, RX_CHUNK)]
                else:
                    pos = off + got
                    target = m.sink[pos: pos + min(remaining, RX_CHUNK)]
                try:
                    n = self._rx_read(target)
                except BlockingIOError:
                    return
                except (ConnectionResetError, OSError):
                    self.worker._conn_broken(self, fires)
                    return
                if n == 0:
                    self.worker._conn_broken(self, fires)
                    return
                if self._csum_pend is not None:
                    self._csum_accum = frames.crc32c(target[:n],
                                                     self._csum_accum)
                got += n
                if got < clen:
                    self._rx_stripe_got = got
                    continue
                self._rx_stripe = None
                self._rx_stripe_got = 0
                if self._csum_pend is not None:
                    pend, self._csum_pend = self._csum_pend, None
                    if self._csum_accum != pend[0]:
                        # Chunk payload corrupt, routing verified: NACK
                        # just this chunk (§19).  The offset was never
                        # recorded in the assembly, so the retransmit
                        # streams into the same sink region; the conn
                        # stays healthy.
                        self._ctr.csum_fail += 1
                        logger.warning(
                            "starway: corrupt striped chunk on conn %s "
                            "(msg %d off %d); requesting retransmit",
                            self.conn_id, asm.msg_id, off)
                        self.send_ctl(frames.pack_snack(asm.msg_id, off),
                                      fires)
                        continue
                self._stripe_rx_tbl().chunk_done(self, asm, off, clen, fires)
                continue
            m = self._rx_msg
            if m is not None:
                remaining = m.length - m.received
                if m.discard or m.sink is None:
                    if self._scratch is None:
                        self._scratch = bytearray(RX_CHUNK)
                    target = memoryview(self._scratch)[: min(remaining, RX_CHUNK)]
                else:
                    target = m.sink[m.received : m.received + min(remaining, RX_CHUNK)]
                try:
                    n = self._rx_read(target)
                except BlockingIOError:
                    return
                except (ConnectionResetError, OSError):
                    self.worker._conn_broken(self, fires)
                    return
                if n == 0:
                    self.worker._conn_broken(self, fires)
                    return
                if self._csum_pend is not None:
                    self._csum_accum = frames.crc32c(target[:n],
                                                     self._csum_accum)
                m.received += n
                if (m.progress is not None and not m.discard
                        and m.sink is not None):
                    # Device-sink overlap: fully-arrived chunks start their
                    # async H2D while the rest of the payload streams in
                    # (device.py DeviceRecvSink.staged; DESIGN.md §12).
                    m.progress(m.received)
                if m.received >= m.length:
                    if self._csum_pend is not None:
                        # Verified BEFORE the matcher completes the
                        # receive: corrupt bytes must never reach user
                        # code as good data (§19).  Poison -- the replay
                        # (sessions) rewrites the sink from the start.
                        pend, self._csum_pend = self._csum_pend, None
                        if self._csum_accum != pend[0]:
                            self._corrupt(fires, "payload checksum (DATA)")
                            return
                    with lock:
                        fires.extend(matcher.on_message_complete(m))
                    self._rx_msg = None
                    self._rx_e2e(m.length)
                    self._sess_commit()
                continue
            if self._ctl is not None:
                ftype, body, got, a = self._ctl
                try:
                    n = self._rx_read(memoryview(body)[got:])
                except BlockingIOError:
                    return
                except (ConnectionResetError, OSError):
                    self.worker._conn_broken(self, fires)
                    return
                if n == 0:
                    self.worker._conn_broken(self, fires)
                    return
                if self._csum_pend is not None:
                    self._csum_accum = frames.crc32c(
                        memoryview(body)[got:got + n], self._csum_accum)
                got += n
                if got < len(body):
                    self._ctl = (ftype, body, got, a)
                    continue
                self._ctl = None
                if self._csum_pend is not None:
                    pend, self._csum_pend = self._csum_pend, None
                    if self._csum_accum != pend[0]:
                        self._corrupt(fires, "control body checksum")
                        return
                # json.loads reads the bytearray directly: no full-body copy.
                # A body that is not a valid JSON OBJECT (bad syntax, a
                # nesting bomb, or the wrong shape -- unpack_json_body
                # raises ValueError for all three) is a protocol
                # violation on THIS conn, not an engine-thread
                # exception: an unhandled raise here escaped the event
                # loop and emergency-closed the whole worker (every conn
                # with it).  The native ctl dispatch breaks the conn on
                # the same non-object shapes; braced-but-invalid JSON is
                # the one residual asymmetry (its tolerant field
                # extractor cannot see syntax).
                try:
                    info = frames.unpack_json_body(body)
                except ValueError:
                    self.worker._conn_broken(self, fires)
                    return
                if ftype == frames.T_HELLO:
                    self.worker._on_hello(self, info, fires)
                elif ftype == frames.T_DEVPULL:
                    self.worker._on_devpull(self, a, info, fires)
                    self._rx_e2e(len(body))
                    self._sess_commit()
                elif ftype == frames.T_RTS:
                    self.worker._on_rts(self, a, info, fires)
                else:
                    self.worker._on_hello_ack(self, info, fires)
                continue
            # header state
            try:
                n = self._rx_read(memoryview(self._hdr)[self._hdr_got :])
            except BlockingIOError:
                return
            except (ConnectionResetError, OSError):
                self.worker._conn_broken(self, fires)
                return
            if n == 0:
                self.worker._conn_broken(self, fires)
                return
            if self._csum_pend is not None:
                # The header of the protected frame is covered too: a
                # corrupted length field must never desync the stream.
                self._csum_accum = frames.crc32c(
                    memoryview(self._hdr)[self._hdr_got:self._hdr_got + n],
                    self._csum_accum)
            self._hdr_got += n
            if self._hdr_got < frames.HEADER_SIZE:
                continue
            self._hdr_got = 0
            ftype, a, b = frames.unpack_header(self._hdr)
            if self._proto is not None:
                # swrefine: one protocol event per dispatched inbound
                # frame, BEFORE the §19 gate and the dispatch chain --
                # the monitor sees exactly what the parser saw
                # (DESIGN.md §22; the native pump_stream taps the same
                # point).
                self._proto.rec(swtrace.EV_PROTO, 0, self.conn_id, 0,
                                "rx:" + frames.FRAME_NAMES.get(ftype,
                                                               "OTHER"))
            if self.csum_ok:
                # §19 verification gate, BEFORE dispatch: arm on T_CSUM,
                # require one for every protected frame, and validate
                # routing fields the moment they are parsed.
                pend = self._csum_pend
                if ftype == frames.T_CSUM:
                    if pend is not None:
                        self._corrupt(fires, "nested checksum prefix")
                        return
                    # Only the low 32 bits are CRC (the native engine
                    # truncates to uint32_t; keeping the full u64 here
                    # made the engines disagree on adversarial prefixes
                    # -- wirefuzz corpus seed).
                    self._csum_pend = (a & 0xFFFFFFFF, b & 0xFFFFFFFF)
                    self._csum_accum = 0
                    continue
                if ftype not in _CSUM_EXEMPT:
                    if pend is None:
                        self._corrupt(fires, "frame without checksum")
                        return
                    if (ftype != frames.T_SDATA
                            and self._csum_accum != pend[1]):
                        self._corrupt(fires, "frame header checksum")
                        return
                    body_follows = (ftype == frames.T_SDATA
                                    or (ftype in _CSUM_BODY and b > 0))
                    if not body_follows:
                        # Header-only frame: the header IS the frame.
                        self._csum_pend = None
                        if self._csum_accum != pend[0]:
                            self._corrupt(fires, "frame checksum")
                            return
            if ftype == frames.T_DATA:
                if self._sess_drop:
                    self._sess_drop = False
                    if b:
                        self._rx_skip = b
                        if self.fc_ok:
                            # The dup was re-debited against the fresh
                            # window at the sender's resume: grant it
                            # back (no memory held -- credit
                            # conservation, DESIGN.md §18).
                            self.send_ctl(frames.pack_credit(b), fires)
                    continue
                overload = False
                spilled = False
                with lock:
                    msg, f = matcher.on_message_start(a, b)
                    fires.extend(f)
                    spilled = (b > 0 and not msg.discard
                               and msg.posted is None
                               and msg.spill is not None)
                    # Tracked only when §18 is in play (fc negotiated or
                    # the cap armed): the seed path must not pay an
                    # engine op per unexpected message.
                    if spilled and (self.fc_ok or self._unexp_cap):
                        # Unexpected spill: charge this conn's window
                        # accounting; the matcher returns the grant when
                        # the bytes leave the queue (fc_release).
                        matcher.fc_track(msg, self, self.fc_rx_gen, b)
                        self.fc_unexp += b
                        # Per-conn cap: the offender is the conn whose
                        # own un-granted residency crossed the line
                        # (total bound = cap x live conns), never an
                        # innocent peer spilling into a full queue.
                        overload = bool(self._unexp_cap
                                        and self.fc_unexp
                                        > self._unexp_cap)
                    if b == 0:
                        fires.extend(matcher.on_message_complete(msg))
                    else:
                        self._rx_msg = msg
                if overload:
                    # STARWAY_UNEXP_BYTES breaker: reset this conn
                    # instead of letting the process OOM (last resort
                    # for peers that never negotiated fc).
                    logger.warning(
                        "starway: unexpected-queue cap exceeded "
                        "(%d > %d); resetting conn %s",
                        self.fc_unexp, self._unexp_cap, self.conn_id)
                    self.worker._conn_broken(self, fires)
                    return
                if b == 0:
                    self._rx_e2e(0)
                    self._sess_commit()
                elif self.fc_ok and not spilled:
                    # Matched at header (streams into the posted buffer)
                    # or probe-discarded: no unexpected memory is held,
                    # so the sender's debit returns immediately.
                    self.send_ctl(frames.pack_credit(b), fires)
            elif ftype == frames.T_FLUSH:
                if self._sess_drop:
                    self._sess_drop = False
                    continue
                self._sess_commit()
                if self._remote_msgs:
                    # Unresolved pulls precede this barrier in the stream:
                    # defer the ACK until they land (the sender's flush must
                    # mean the payload is resident here), and force-start
                    # any still waiting for a matching receive.
                    self.defer_flush_ack(a)
                    self.worker._force_start_pulls(self, fires)
                else:
                    self.send_flush_ack(a, fires)
            elif ftype == frames.T_FLUSH_ACK:
                if self._sess_drop:
                    self._sess_drop = False
                    continue
                self._sess_commit()
                self.worker._on_flush_ack(self, a, fires)
            elif ftype == frames.T_SEQ:
                if not self._on_seq(a, fires):
                    return
            elif ftype == frames.T_ACK:
                if self.sess is not None:
                    self._on_ack(a, fires)
            elif ftype == frames.T_BYE:
                # Peer's clean local close on a session conn: the session
                # is over -- the imminent EOF must take the seed/keepalive
                # death contract (prompt "not connected", no fault dump),
                # not a grace-window suspend + redial.
                if self.sess is not None and not self.sess.expired:
                    self.sess.expired = True
                    getattr(self.worker, "_sessions", {}).pop(
                        self.sess.sid, None)
            elif ftype == frames.T_SDATA:
                # Striped chunk (DESIGN.md §17): the 24-byte sub-header
                # follows; a body not longer than it is a protocol
                # violation (no sender emits zero-length chunks, and a
                # zero-length read here stalled the sm transport forever
                # while TCP misread it as EOF -- wirefuzz corpus seed).
                if b <= frames.SDATA_SUB_SIZE:
                    self.worker._conn_broken(self, fires)
                    return
                self._sdata = (a, bytearray(frames.SDATA_SUB_SIZE), 0, b)
            elif ftype == frames.T_SACK:
                if not self._fc_on_sack(a, fires):
                    root = self.stripe_root()
                    if root.stripe is not None:
                        root.stripe.on_sack(a, fires)
            elif ftype == frames.T_SNACK:
                # §19 chunk-level retransmit request from the receiver.
                self._on_snack(a, b, fires)
            elif ftype == frames.T_CREDIT:
                self._on_credit(a, fires)
            elif ftype == frames.T_CTS:
                self._on_cts(a, fires)
            elif ftype == frames.T_PING:
                # Liveness probe: answer immediately.  _rx_read already
                # refreshed last_rx, so receiving PINGs also proves the
                # peer alive to us.  A timestamped PING gets its echo +
                # our own clock reading (the swscope sample channel).
                self.send_ctl(frames.pack_pong(a, time.perf_counter_ns()),
                              fires)
            elif ftype == frames.T_PONG:
                self._on_pong(a, b)  # proof of life recorded by _rx_read
            elif ftype in (frames.T_HELLO, frames.T_HELLO_ACK,
                           frames.T_DEVPULL, frames.T_RTS):
                # A ctl frame's JSON body is small and never empty; a
                # zero length used to issue a 0-byte read (EOF-alike on
                # TCP, a permanent stall on sm rings, a silent drop in
                # the C++ engine) and an unchecked length is a remote
                # allocation primitive -- both are protocol violations
                # now, in BOTH engines (frames.CTL_MAX; wirefuzz seeds).
                if b == 0 or b > frames.CTL_MAX:
                    self.worker._conn_broken(self, fires)
                    return
                if ftype == frames.T_DEVPULL and self._sess_drop:
                    self._sess_drop = False
                    self._rx_skip = b
                    continue
                self._ctl = (ftype, bytearray(b), 0, a)
            else:
                self.worker._conn_broken(self, fires)
                return

    # --------------------------------------------------------------- close
    def _cancel_tx_state(self, fires: list,
                         reason: str = REASON_CANCELLED,
                         count: bool = True) -> None:
        """Cancel every queued / journaled / parked tx item exactly once
        (cancel() is idempotent; journal entries may also sit in tx)."""
        items = list(self.tx)
        if self.sess is not None:
            items.extend(self.sess.journal)
            items.extend(self.sess.waiting)
            self.sess.journal.clear()
            self.sess.journal_bytes = 0
            self.sess.waiting.clear()
        if self.fc_waiting:
            # Flow-control-parked sends take the same fate as queued ones.
            items.extend(self.fc_waiting)
            self.fc_waiting.clear()
        if self.fc_rts:
            # Announced rendezvous sends: drop the pins, cancel the ops
            # (a delivery item may also sit in tx -- cancel is
            # idempotent, one count).
            items.extend(ent[0] for ent in self.fc_rts.values())
            self.fc_rts.clear()
        self.fc_rx.clear()  # dedup index only; the matcher owns the records
        for item in items:
            before = len(fires)
            item.cancel(fires, reason)
            if count and len(fires) > before:
                self._ctr.ops_cancelled += 1
        self.tx.clear()
        if self.stripe is not None:
            # Primary terminal teardown: un-SACKed striped sources take
            # the same fate as queued sends (counts ops_cancelled).
            self.stripe.cancel_all(fires, reason)
        if self.stripe_rx is not None:
            self.stripe_rx.purge()

    def close(self, fires: list) -> None:
        """Close at local shutdown.

        Unfinished tagged sends are cancelled and the socket is reset so the
        peer cannot observe a partial message as delivered (the reference's
        close-cancels-in-flight semantics, src/bindings/main.cpp:483-507).
        With no data in flight the close is graceful: kernel-buffered bytes
        still drain to the peer.
        """
        abort = self.has_unfinished_data_tx()
        if (self.alive and self.sock is not None and self.sess is not None
                and not self.sess.suspended and not self.sess.expired
                and not abort and (not self.tx or self.tx[0].off == 0)):
            # Clean close on a session conn: tell the peer the session is
            # over (T_BYE) so it fails over to the seed death contract
            # instead of suspending for the grace window.  Best-effort --
            # a lost BYE only costs the peer the grace-expiry fallback.
            try:
                bye = frames.pack_bye()
                if self.csum_ok:
                    bye = frames.pack_csum_for(bye) + bye
                self.sock.sendall(bye)
            except OSError:
                pass
        self._cancel_tx_state(fires)
        if self.alive:
            self.alive = False
            self.worker._unregister_conn_io(self)
            try:
                if self.sock is not None:
                    if abort:
                        self.sock.setsockopt(
                            socket.SOL_SOCKET,
                            socket.SO_LINGER,
                            socket_linger_struct(),
                        )
                    self.sock.close()
            except OSError:
                pass
            self.sock = None
        self._close_sm()

    def mark_dead(self, fires: list) -> None:
        if self.alive:
            self.alive = False
            self.worker._unregister_conn_io(self)
            # A §19 poison owns the cancel reason: in-flight ops report
            # "corrupt", not a generic cancel (tests/test_integrity.py).
            self._cancel_tx_state(fires,
                                  self.poison_reason or REASON_CANCELLED)
            if self._rx_msg is not None:
                with self.worker.lock:
                    self.worker.matcher.purge_inflight(self._rx_msg)
                self._rx_msg = None
            try:
                if self.sock is not None:
                    self.sock.close()
            except OSError:
                pass
            self.sock = None
        self._close_sm()

    def transports(self) -> list[tuple[str, str]]:
        if self.sm_negotiated:
            return [("shm", "sm")]
        dev = "lo" if self.remote_addr.startswith("127.") else "eth0"
        return [(dev, "tcp")]


def socket_linger_struct() -> bytes:
    import struct as _s

    return _s.pack("ii", 1, 0)  # l_onoff=1, l_linger=0 -> RST on close


class InprocConn(BaseConn):
    kind = "inproc"

    def __init__(self, worker, peer_worker_ref, mode: str):
        super().__init__(worker, mode)
        self.peer_worker_ref = peer_worker_ref  # weakref.ref
        self.peer_conn: Optional["InprocConn"] = None

    def send_data(self, tag: int, payload, done, fail, owner, fires: list,
                  kick: bool = True) -> None:
        # ``kick`` is the TcpConn deferred-push knob; in-process delivery
        # is synchronous, so there is nothing to defer.
        peer = self.peer_worker_ref()
        if not self.alive or peer is None or peer.status != state.RUNNING:
            if fail is not None:
                fires.append(lambda: fail(REASON_NOT_CONNECTED + " (peer closed)"))
            return
        with peer.lock:
            peer_fires = peer.matcher.deliver(tag, payload)
        fires.extend(peer_fires)
        nbytes = len(payload) if isinstance(payload, memoryview) else int(payload.nbytes)
        self._ctr.bytes_tx += nbytes
        self._ctr.sends_completed += 1
        # §25: synchronous delivery -- local completion at post (bucket 0).
        self._hists.send_local_us[0] += 1
        peer_ctr = getattr(peer, "counters", None)
        if peer_ctr is not None:
            peer_ctr.bytes_rx += nbytes
        if done is not None:
            fires.append(done)

    def send_flush(self, seq: int, fires: list) -> None:
        # In-process delivery is synchronous and FIFO on the engine thread:
        # by the time the flush op is processed every prior send has been
        # ingested by the peer's matcher, so the barrier is already met.
        self.flush_acked = seq
        self.worker._on_flush_ack(self, seq, fires)

    def close(self, fires: list) -> None:
        self.alive = False
        if self.peer_conn is not None:
            self.peer_conn.alive = False

    def mark_dead(self, fires: list) -> None:
        self.close(fires)

    def transports(self) -> list[tuple[str, str]]:
        return [("shm", "inproc")]
