"""ServerEndpoint: introspectable snapshot of an accepted peer.

Mirrors the reference's ``ServerEndpoint`` (src/bindings/main.hpp:292-304,
src/starway/_bindings.pyi:10-21): name, local/remote socket coordinates (empty
in worker-address mode, README.md:141-143), and negotiated transports via
``view_transports()``.  Instances are hashable and ordered so they can live in
sets and round-trip through Python, like the reference's ``std::set`` registry
ordered by endpoint pointer (src/bindings/main.cpp:796-809).

The reference stores dangling ``char const*`` views for name/addr (a noted
defect, SURVEY.md "Reference defects"); here everything is owned ``str``.
"""

from __future__ import annotations


class ServerEndpoint:
    __slots__ = ("_conn",)

    def __init__(self, conn):
        self._conn = conn

    @property
    def name(self) -> str:
        return self._conn.peer_name

    @property
    def local_addr(self) -> str:
        return self._conn.local_addr

    @property
    def local_port(self) -> int:
        return self._conn.local_port

    @property
    def remote_addr(self) -> str:
        return self._conn.remote_addr

    @property
    def remote_port(self) -> int:
        return self._conn.remote_port

    def view_transports(self) -> list[tuple[str, str]]:
        """Negotiated (device, transport) pairs, e.g. ``[("shm", "inproc")]``
        or ``[("lo", "tcp")]``; the device plane reports ``("tpu:N", "ici")``.
        Analogue of the reference's up-to-8 ``(device, transport)`` pairs
        (src/bindings/main.cpp:796-804)."""
        return self._conn.transports()

    def __hash__(self) -> int:
        return hash(self._conn.conn_id)

    def __eq__(self, other) -> bool:
        return isinstance(other, ServerEndpoint) and other._conn.conn_id == self._conn.conn_id

    def __lt__(self, other: "ServerEndpoint") -> bool:
        return self._conn.conn_id < other._conn.conn_id

    def __repr__(self) -> str:
        return (
            f"<ServerEndpoint name={self.name!r} remote={self.remote_addr}:{self.remote_port} "
            f"transports={self.view_transports()}>"
        )
