"""swtrace: per-op lifecycle tracing, counter registry, flight recorder.

Observability spine of the host runtime (DESIGN.md §13).  Three pieces,
all spanning both engines:

* **Trace ring** -- a bounded per-worker event buffer recording each op's
  lifecycle (``recv_post`` -> ``recv_match`` -> ``recv_done``, ``send_post``
  -> ``send_done``, flush barriers, failures, connection churn, and the
  data-plane stage spans from perf.record_stage).  Opt-in via
  ``STARWAY_TRACE=1`` (or implicitly when ``STARWAY_FLIGHT_DIR`` is set);
  when off, every hot-path hook is a single ``is None`` check -- no per-op
  allocation, no syscall (pinned by tests/test_trace.py's overhead guard).
  Appends are single ``deque.append`` calls on a ``maxlen`` deque:
  GIL-atomic and lock-free, safe from any thread, and -- unlike user
  callbacks -- permitted while a worker lock is held (no user code runs).
  The C++ engine records the same event vocabulary into its own ring
  (native/sw_engine.cpp ``TraceRing``), surfaced through the ``sw_trace``
  ABI call.

* **Counter registry** -- the fixed ``COUNTER_NAMES`` vocabulary below,
  implemented identically in core/engine.py (``Worker.counters``) and
  native/sw_engine.cpp (``Counters`` + the ``sw_counters`` ABI call), and
  merged into ``evaluate_perf_detail()["counters"]``.  The vocabulary is
  part of the cross-engine contract: swcheck's ``contract-trace`` pass
  diffs it (and the ``EV_*`` event types) against the C++ sources, so a
  counter added to one engine only is a merge-gate finding.

* **Flight recorder** -- on the first op failure with a non-cancel reason,
  on engine emergency close, and on ``close()`` after a fault, the last-N
  trace events plus a counter snapshot are dumped to a JSON file under
  ``STARWAY_FLIGHT_DIR`` for post-mortem forensics (the fault paths of
  DESIGN.md §10).  One dump per (worker, trigger); dump failures are
  swallowed -- the recorder must never take the engine down with it.

Export tooling lives in starway_tpu/trace.py (``python -m
starway_tpu.trace``): ring/flight dumps -> Chrome ``trace_event`` JSON.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from collections import deque
from pathlib import Path
from typing import Optional

from .. import config

# ------------------------------------------------------ event vocabulary
#
# Shared with the C++ engine (native/sw_engine.cpp kEv* literals); the
# mapping is mechanical (EV_SEND_POST <-> kEvSendPost) and machine-checked
# by `python -m starway_tpu.analysis` (rule contract-trace).

EV_SEND_POST = "send_post"    # tagged send (or DEVPULL descriptor) submitted
EV_SEND_DONE = "send_done"    # send locally complete (eager: handed to
#                               transport; rndv: transmission begun)
EV_RECV_POST = "recv_post"    # receive posted on the worker
EV_RECV_MATCH = "recv_match"  # receive claimed an inbound message (or vice
#                               versa) in the matcher
EV_RECV_DONE = "recv_done"    # receive delivered (tag = sender tag)
EV_FLUSH_POST = "flush_post"  # delivery barrier submitted
EV_FLUSH_DONE = "flush_done"  # barrier acknowledged by every target conn
EV_OP_FAIL = "op_fail"        # any op failed; reason carried verbatim
EV_CONN_UP = "conn_up"        # connection handshaken / attached
EV_CONN_DOWN = "conn_down"    # connection broken (peer death / reset)
EV_STAGE = "stage_span"       # data-plane stage span (perf.record_stage):
#                               reason = stage name, dur = span seconds
EV_SESS_RESUME = "sess_resume"  # session conn resumed after a reconnect
#                               (conn = conn id; nbytes = frames replayed)
EV_SESS_EXPIRE = "sess_expire"  # session expired (grace elapsed / new epoch)
EV_E2E = "e2e"                # swscope end-to-end marker (DESIGN.md §15):
#                               tag = per-conn per-direction wire ordinal,
#                               reason = "<trace-conn id>:tx|rx|sup" --
#                               equal (id, ordinal) at the two ends of a
#                               conn is ONE message; trace --merge draws
#                               the send->recv flow from the pair.  ":sup"
#                               marks a session replay of an already-
#                               counted frame (superseded, not recounted).
EV_CLOCK = "clock_sample"     # swscope clock-offset sample from a
#                               timestamped PING/PONG round trip: reason =
#                               "<trace-conn id>:<offset_us>:<err_us>"
#                               (peer_clock ~= local_clock + offset).
EV_PROTO = "proto"            # swrefine protocol event (DESIGN.md §22):
#                               conn = conn id, reason = the canonical
#                               event -- "rx:<FRAME>" at inbound dispatch,
#                               "tx:<FRAME>" at ctl-plane handoff,
#                               "st:hello-sent"/"st:estab" at conn
#                               creation, "lost"/"resume"/"expire"/"down"
#                               for the lifecycle.  Armed only by
#                               STARWAY_PROTO_TRACE / STARWAY_MONITOR
#                               (proto_active below); analysis/refine.py
#                               replays the channel through the monitor
#                               automaton compiled from both engines'
#                               protocol state machines.
EV_STALL = "stall"            # swpulse stall-sentinel alert (DESIGN.md
#                               §25): conn = suspect conn id (0 = worker-
#                               wide), reason = one of STALL_REASONS.
#                               Armed only by STARWAY_STALL_MS.

# ----------------------------------------------------- counter vocabulary
#
# One name list, two implementations (engine.py Worker.counters and the
# C++ kCounterNames/Counters pair).  `staging_hits`/`staging_misses` and
# `reconnects` are PROCESS-GLOBAL (the staging pool and the api-layer
# reconnect loop are not per-worker); merge_global_counters overlays them
# onto every worker snapshot so one dict answers "what happened here".

COUNTER_NAMES = (
    "sends_posted",       # tagged sends + DEVPULL descriptors submitted
    "sends_completed",    # send payloads fully handed to a transport
    "recvs_posted",       # receives posted
    "recvs_completed",    # receives delivered
    "flushes_posted",     # flush barriers submitted
    "flushes_completed",  # flush barriers acknowledged
    "ops_timed_out",      # ops failed by a deadline (REASON_TIMEOUT)
    "ops_cancelled",      # ops cancelled by local close
    "bytes_tx",           # payload/frame bytes handed to transports
    "bytes_rx",           # payload/frame bytes read from transports
    "gather_passes",      # gathered sendmsg passes (TX pump)
    "gather_items",       # iovecs submitted across gathered passes
    "staging_hits",       # staging-pool buffer reuses (process-global)
    "staging_misses",     # staging-pool fresh allocations (process-global)
    "ka_misses",          # peers declared dead by keepalive liveness
    "reconnects",         # aconnect retry attempts (process-global)
    "sessions_resumed",   # session conns resumed after a reconnect
    "frames_replayed",    # journaled frames re-queued at session resume
    "dup_frames_dropped", # duplicate-seq frames dropped by the receiver
    "acks_tx",            # cumulative session ACK frames sent
    "acks_rx",            # cumulative session ACK frames received
    "stripe_chunks_tx",   # striped chunks fully handed to a lane (§17)
    "stripe_chunks_rx",   # striped chunks ingested into an assembly
    "rail_resteals",      # chunks re-queued off a dead rail onto survivors
    "sends_parked",       # sends parked by the §18 credit window
    "sheds",              # parked sends failed by deadline-aware shedding
    "csum_fail",          # §19 integrity verification failures detected
    "chunk_retx",         # §19 striped chunks retransmitted after a NACK
    "reshard_bytes",      # §20 swshard bytes staged through schedules
    #                       (process-global: the executor runs above the
    #                       workers, like the staging pool does)
    "reshard_rounds",     # §20 swshard schedule rounds executed
    "io_syscalls",        # §23 hot-path I/O syscalls issued
    #                       (send/sendmsg/recv/recv_into on the data path)
    "hot_copies",         # §23 hot-path payload byte-copies (sm ring
    #                       put/take; the tcp data path is copy-free)
    "uring_submits",      # §24 io_uring_enter batched-submit calls
    #                       (native-only lever; this engine declares the
    #                       name and leaves it 0, like staging_* on the
    #                       C++ side)
    "uring_sqes",         # §24 sendmsg SQEs landed through the ring
    "zc_sends",           # §24 MSG_ZEROCOPY payload sendmsg calls
    "zc_notifies",        # §24 zerocopy completion ranges drained from
    #                       the errqueue (COPIED fallbacks included)
    "busypoll_hits",      # §24 events harvested inside the spin window
    "stall_alerts",       # §25 stall-sentinel alerts raised (0 unless
    #                       STARWAY_STALL_MS armed the sentinel)
)


class Counters:
    """Fixed-vocabulary integer counters (one instance per worker, plus
    the process-global ``GLOBAL``).  Plain attribute increments: writers
    are effectively single-threaded per counter (submit counters on the
    app thread, data-plane counters on the engine thread), so the
    read-modify-write race window is theoretical; telemetry tolerates it.
    """

    __slots__ = COUNTER_NAMES

    def __init__(self):
        for name in COUNTER_NAMES:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in COUNTER_NAMES}


# --------------------------------------------------- histogram vocabulary
#
# swpulse (DESIGN.md §25): always-on log-bucketed distributions, bumped
# unconditionally at the contract points in BOTH engines (engine.py /
# conn.py / matching.py / lane.py <-> native/sw_engine.cpp, surfaced
# through ``sw_hists`` <-> ``Worker.hists_snapshot``).  Like COUNTER_NAMES
# the vocabulary -- and the bucket layout -- is cross-engine contract
# surface diffed by swcheck's ``contract-pulse`` pass against
# ``kHistNames[]`` / ``kHistBuckets``.  One bump is one clock read + one
# integer increment into a fixed per-worker array: no allocation, no lock,
# no branch on the seed path (the arrays always exist).  Latencies are in
# MICROSECONDS, sizes in BYTES; bucket i holds values with bit_length i
# (0 -> bucket 0), so bucket boundaries are powers of two and percentiles
# are derived at read time from the bucket upper bounds (hist_percentiles).

HIST_NAMES = (
    "send_local_us",   # send post -> local completion (eager: handed to
    #                    transport; rndv: transmission begun -- the §10
    #                    local-completion contract, measured)
    "recv_wait_us",    # recv post -> matcher claim (posted-first waits;
    #                    unexpected-first matches at ~0)
    "flush_us",        # flush barrier post -> all-target acknowledgement
    "park_us",         # §18 credit-window park residency (parked ->
    #                    unparked or shed)
    "pin_us",          # payload pin residency: §17 stripe pinned -> SACKed
    #                    and §24 zerocopy pinned -> errqueue-released
    #                    (native lever; this engine records stripe only)
    "msg_bytes",       # payload size per posted send
)

#: Buckets per histogram; bucket i covers values of ``bit_length() == i``
#: (i.e. [2^(i-1), 2^i)), with bucket 0 = zero and the last bucket open.
HIST_BUCKETS = 64


def hist_bucket(value: int) -> int:
    """Log-bucket index for a nonnegative integer (negative clamps to 0)."""
    if value <= 0:
        return 0
    b = value.bit_length()
    return b if b < HIST_BUCKETS else HIST_BUCKETS - 1


class Hists:
    """Fixed-vocabulary log-bucket histograms (one instance per worker).
    Plain list-element increments under the GIL, same tolerance story as
    :class:`Counters`; the C++ twin uses relaxed atomics."""

    __slots__ = HIST_NAMES

    def __init__(self):
        for name in HIST_NAMES:
            setattr(self, name, [0] * HIST_BUCKETS)

    def snapshot(self) -> dict:
        return {name: list(getattr(self, name)) for name in HIST_NAMES}


def hist_percentiles(buckets) -> dict:
    """p50/p90/p99/p999 + count for one histogram, derived at read time.
    Each percentile reports the upper bound of the bucket the rank lands
    in (2^i - 1) -- an over-estimate by at most 2x, which is the log-
    bucket deal."""
    total = sum(buckets)
    out = {"count": total, "p50": 0, "p90": 0, "p99": 0, "p999": 0}
    if total == 0:
        return out
    targets = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99), ("p999", 0.999))
    ti = 0
    seen = 0
    for i, n in enumerate(buckets):
        if not n:
            continue
        seen += n
        bound = (1 << i) - 1 if i else 0
        while ti < len(targets) and seen >= targets[ti][1] * total:
            out[targets[ti][0]] = bound
            ti += 1
        if ti == len(targets):
            break
    return out


def hist_summary(snapshot: dict) -> dict:
    """Percentile view of a ``hists_snapshot()`` dict -- the compact shape
    telemetry samples and the metrics viewer carry."""
    return {name: hist_percentiles(buckets)
            for name, buckets in snapshot.items()}


# ------------------------------------------------- stall-reason vocabulary
#
# swpulse sentinel (DESIGN.md §25): the no-progress conditions the
# detector can flag, carried verbatim as the EV_STALL event reason and in
# stall reports.  Cross-engine contract surface like the names above
# (kStallReasons[] in sw_engine.cpp, diffed by contract-pulse).

STALL_REASONS = (
    "stall-flush",     # a flush barrier outlived the threshold with no
    #                    counter progress behind it
    "stall-credit",    # §18 parked sends aged past the threshold with no
    #                    credit arrival
    "stall-pin",       # stripe/zerocopy/journal pins undrained with no
    #                    progress past the threshold
    "stall-unexp",     # unexpected-queue residency with no recv progress
    #                    past the threshold
)


#: Process-global counters (staging pool, api-layer reconnects).
GLOBAL = Counters()

_GLOBAL_NAMES = ("staging_hits", "staging_misses", "reconnects",
                 "reshard_bytes", "reshard_rounds")


def merge_global_counters(snap: dict) -> dict:
    """Overlay the process-global counters onto a worker snapshot."""
    for name in _GLOBAL_NAMES:
        snap[name] = getattr(GLOBAL, name)
    return snap


# ------------------------------------------------------------ trace ring


def active() -> bool:
    """Tracing hooks armed for new workers?  True when ``STARWAY_TRACE``
    is on, a flight directory is configured (the recorder needs the
    ring's last-N events even when nobody asked for a full trace), the
    swrefine protocol-event channel is armed (its events ride this ring,
    DESIGN.md §22), or the swpulse stall sentinel is armed (its EV_STALL
    alerts and the "last events" in stall reports need a ring to land in,
    DESIGN.md §25)."""
    return (config.trace_enabled() or bool(config.flight_dir())
            or config.proto_trace_enabled() or config.stall_ms() > 0)


def proto_active() -> bool:
    """swrefine protocol-event channel armed for new conns?  Kept
    separate from :func:`active` so plain STARWAY_TRACE runs keep their
    seed event streams (the proto channel adds one event per frame); the
    env-unset path stays a single ``is None`` check per frame."""
    return config.proto_trace_enabled()


class TraceRing:
    """Bounded per-worker event ring.

    Events are ``(t, ev, tag, conn, nbytes, reason, dur)`` tuples with
    ``t`` from ``time.perf_counter()`` (CLOCK_MONOTONIC -- the same epoch
    the C++ ring stamps with ``steady_clock``, so one process's rings
    share a timeline).  ``dur`` is nonzero only for EV_STAGE spans.
    """

    __slots__ = ("events",)

    def __init__(self, capacity: int):
        self.events: deque = deque(maxlen=max(16, int(capacity)))

    def rec(self, ev: str, tag: int = 0, conn: int = 0, nbytes: int = 0,
            reason: str = "", dur: float = 0.0) -> None:
        self.events.append(
            (time.perf_counter(), ev, tag, conn, nbytes, reason, dur))

    def snapshot(self) -> list:
        return list(self.events)


def worker_ring() -> Optional[TraceRing]:
    """A fresh ring for a new worker, or None when tracing is off (the
    worker then carries no per-op hooks at all)."""
    if not active():
        return None
    return TraceRing(config.trace_ring_size())


def wrap_op(worker, ring: TraceRing, done_ev: str, tag: int, conn: int,
            nbytes: int, done, fail):
    """Wrap an op's (done, fail) callbacks to record its terminal event
    (and arm the flight recorder on non-cancel failures).  Only called
    when tracing is active -- the off path never allocates these closures.
    """

    def traced_done(*args):
        if done_ev == EV_RECV_DONE and len(args) >= 2:
            ring.rec(done_ev, args[0], conn, args[1])
        else:
            ring.rec(done_ev, tag, conn, nbytes)
        if done is not None:
            done(*args)

    def traced_fail(reason: str):
        ring.rec(EV_OP_FAIL, tag, conn, nbytes, reason)
        if "cancel" not in reason.lower():
            worker._faulted = True
            flight_dump("op-failed", worker, reason)
        if fail is not None:
            fail(reason)

    return traced_done, traced_fail


# ---------------------------------------------------------- ring registry
#
# `python -m starway_tpu.bench --trace` (and the trace CLI) need every
# ring the process produced, including workers already closed by the time
# the report is written.  Live workers are held weakly; closed workers
# snapshot their ring into a bounded retired list via retire().

_reg_lock = threading.Lock()
_live: list = []      # weakref.ref(worker)
_retired: list = []   # {"worker": label, "events": [...]}
_RETIRED_CAP = 64


def register_worker(worker) -> None:
    if not active():
        return
    with _reg_lock:
        _live.append(weakref.ref(worker))
        _live[:] = [r for r in _live if r() is not None]


def retire(worker) -> None:
    """Snapshot a closing worker's ring into the retired list so its
    events survive the worker object (bench reports run after close).
    With STARWAY_MONITOR armed this is also the automatic conformance
    checkpoint: the worker's protocol events replay through the monitor
    before the ring is retired (DESIGN.md §22)."""
    if not active() or getattr(worker, "_trace_retired", False):
        return
    worker._trace_retired = True
    try:
        events = worker.trace_events()
    except Exception:
        events = []
    if events and config.monitor_enabled():
        from . import monitor

        monitor.check_worker(worker, events)
    if not events:
        return
    try:
        hists = worker.hists_snapshot()
    except Exception:
        hists = {}
    with _reg_lock:
        _retired.append({"worker": worker.trace_label, "events": events,
                         "hists": hists})
        del _retired[:-_RETIRED_CAP]


def dump_all() -> list:
    """``[{"worker": label, "events": [...]}, ...]`` for every traced
    worker this process has seen (retired first, then live)."""
    with _reg_lock:
        out = list(_retired)
        live = [r() for r in _live]
    for w in live:
        if w is None or getattr(w, "_trace_retired", False):
            continue
        try:
            events = w.trace_events()
        except Exception:
            continue
        if events:
            try:
                hists = w.hists_snapshot()
            except Exception:
                hists = {}
            out.append({"worker": w.trace_label, "events": events,
                        "hists": hists})
    return out


def reset() -> None:
    """Drop registry state (test isolation)."""
    with _reg_lock:
        _live.clear()
        _retired.clear()


def write_ring_dump(path) -> Path:
    """Dump every traced worker's ring to one JSON file -- the per-process
    input ``python -m starway_tpu.trace --merge`` stitches (each process
    of a distributed run writes one before exiting)."""
    payload = {
        "pid": os.getpid(),
        "time": time.time(),
        "workers": [
            {"worker": d["worker"], "events": [list(e) for e in d["events"]],
             # §25 swpulse distributions ride every ring dump so a
             # post-mortem (and trace --merge) keeps the percentile
             # picture next to the event timeline.
             "hists": d.get("hists", {})}
            for d in dump_all()
        ],
    }
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload))
    return path


# -------------------------------------------------------- flight recorder

_flight_seq = itertools.count(1)


def flight_dump(trigger: str, worker, reason: str = "") -> Optional[Path]:
    """Dump the worker's last-N trace events + counter snapshot to
    ``STARWAY_FLIGHT_DIR`` (no-op when unset).  Once per (worker,
    trigger); never raises -- forensics must not add failure modes."""
    flight_dir = config.flight_dir()
    if not flight_dir:
        return None
    trigs = getattr(worker, "_flight_trigs", None)
    if trigs is None:
        trigs = worker._flight_trigs = set()
    if trigger in trigs:
        return None
    trigs.add(trigger)
    try:
        label = getattr(worker, "trace_label", "worker")
        try:
            events = worker.trace_events()
        except Exception:
            events = []
        try:
            counters = worker.counters_snapshot()
        except Exception:
            counters = {}
        try:
            hists = worker.hists_snapshot()
        except Exception:
            hists = {}
        # Telemetry trend + the per-conn gauge snapshot at trigger time:
        # a post-mortem then shows the queue/journal trajectory INTO the
        # failure, not just the instant (DESIGN.md §15).
        try:
            gauges = worker.gauges_snapshot()
        except Exception:
            gauges = {}
        try:
            from . import telemetry

            samples = telemetry.recent_samples()
        except Exception:
            samples = []
        payload = {
            "trigger": trigger,
            "worker": label,
            "reason": reason,
            "pid": os.getpid(),
            "time": time.time(),
            "counters": counters,
            "hists": hists,
            "gauges": gauges,
            "telemetry": samples,
            "events": [list(e) for e in events],
        }
        out_dir = Path(flight_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / f"flight-{label}-{os.getpid()}-{next(_flight_seq)}.json"
        path.write_text(json.dumps(payload, indent=1))
        return path
    except Exception:
        return None
