"""Benchmark CLI: ``python -m starway_tpu.bench``.

Same surface as the reference CLI (src/starway/bench.py): roles
``server`` / ``client`` / ``loopback``, socket or worker-address bootstrap
(hex-encoded blob), per-scenario overrides with K/M/G size suffixes, JSON
control frames over tagged messages, and a JSON report with optional
per-iteration traces.  ``--tls`` maps to ``STARWAY_TLS`` (the reference's
``UCX_TLS`` analogue, benchmark.md:114-126).

The control protocol is unchanged in shape: the client drives, sending a JSON
frame on CONTROL_TAG naming the scenario + overrides; the server replies on
READY_TAG, runs its half, then signals DONE_TAG.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from pathlib import Path
from typing import Any, Mapping, Sequence

import numpy as np

_SIZE_SUFFIXES = {
    "kib": 1 << 10, "kb": 1 << 10, "ki": 1 << 10, "k": 1 << 10,
    "mib": 1 << 20, "mb": 1 << 20, "mi": 1 << 20, "m": 1 << 20,
    "gib": 1 << 30, "gb": 1 << 30, "gi": 1 << 30, "g": 1 << 30,
}


def parse_size(value: str) -> int:
    """Parse '512M', '1g', '4096' into bytes (reference: bench.py:29-49)."""
    text = value.strip().lower().replace("_", "")
    for suffix in sorted(_SIZE_SUFFIXES, key=len, reverse=True):
        if text.endswith(suffix):
            return int(float(text[: -len(suffix)]) * _SIZE_SUFFIXES[suffix])
    return int(float(text))


def parse_worker_address(value: str) -> bytes:
    return bytes.fromhex(value.replace(":", "").replace(" ", "").strip())


def _encode_ctl(payload: Mapping[str, Any]) -> np.ndarray:
    raw = json.dumps(payload, separators=(",", ":"), sort_keys=True).encode()
    return np.frombuffer(raw, dtype=np.uint8).copy()


def _decode_ctl(buffer: np.ndarray, length: int) -> dict:
    return json.loads(bytes(memoryview(buffer)[:length]).decode())


def build_parser() -> argparse.ArgumentParser:
    from .benchmarks import list_scenarios

    p = argparse.ArgumentParser(description="starway-tpu benchmark suite")
    p.add_argument("--role", choices=("server", "client", "loopback"), required=True)
    p.add_argument("--addr", default="0.0.0.0", help="Server listen address (socket mode).")
    p.add_argument("--port", type=int, default=17777, help="TCP port for socket mode.")
    p.add_argument("--server-host", default="127.0.0.1", help="Server hostname (client role).")
    p.add_argument("--listen-mode", choices=("socket", "worker"), default="socket")
    p.add_argument("--connect-mode", choices=("socket", "worker"), default="socket")
    p.add_argument("--worker-address", help="Hex-encoded worker address blob for connect-mode=worker.")
    p.add_argument("--tls", help="Transport list written to STARWAY_TLS (e.g. 'tcp' or 'inproc,tcp').")
    p.add_argument(
        "--payload", choices=("host", "device"),
        help="Buffer kind for large-array/streaming-duplex: host numpy (default) or jax.Array device buffers.",
    )
    p.add_argument("--scenarios", nargs="*", help="Scenarios to run (default: all). Options: " + ", ".join(list_scenarios()))
    p.add_argument("--large-bytes", type=parse_size)
    p.add_argument("--large-iterations", type=int)
    p.add_argument("--large-warmup", type=int)
    p.add_argument("--small-bytes", type=parse_size)
    p.add_argument("--small-iterations", type=int)
    p.add_argument("--small-warmup", type=int)
    p.add_argument("--small-concurrency", type=int)
    p.add_argument("--flag-iterations", type=int)
    p.add_argument("--flag-warmup", type=int)
    p.add_argument("--stream-bytes", type=parse_size)
    p.add_argument("--stream-iterations", type=int)
    p.add_argument("--stream-warmup", type=int)
    p.add_argument("--striped-bytes", type=parse_size)
    p.add_argument("--striped-iterations", type=int)
    p.add_argument("--striped-warmup", type=int)
    p.add_argument("--flood-bytes", type=parse_size)
    p.add_argument("--flood-messages", type=int)
    p.add_argument("--flood-iterations", type=int)
    p.add_argument(
        "--reshard-bytes", type=parse_size, metavar="BYTES",
        help="Array size for the 'reshard' scenario (redistributed whole "
             "each iteration under the §20 O(shard) staging bound).",
    )
    p.add_argument("--reshard-blocks", type=int, metavar="N",
                   help="Shards per side for the 'reshard' scenario "
                        "(row-sharded source -> column-sharded sink).")
    p.add_argument("--reshard-iterations", type=int)
    p.add_argument("--reshard-warmup", type=int)
    p.add_argument(
        "--fc-window", type=parse_size, metavar="BYTES",
        help="Arm §18 receiver-driven flow control (STARWAY_FC_WINDOW) for "
             "the run; see the 'flooded' scenario (DESIGN.md §18).",
    )
    p.add_argument(
        "--rails", type=int, metavar="N",
        help="Open N transport lanes per connection (STARWAY_RAILS) and arm "
             "multi-rail striping (STARWAY_STRIPE_THRESHOLD defaults to 1 MiB "
             "when unset); see the 'striped' scenario (DESIGN.md §17).",
    )
    p.add_argument(
        "--uring", action="store_true",
        help="Arm the §24 io_uring batched-TX lever (STARWAY_IOURING=1); "
             "native engine only, silently falls back to epoll when the "
             "kernel probe fails.",
    )
    p.add_argument(
        "--zerocopy", action="store_true",
        help="Arm the §24 MSG_ZEROCOPY lever (STARWAY_ZEROCOPY=1) for "
             ">= rndv-threshold payloads; native engine only.",
    )
    p.add_argument(
        "--busypoll", type=int, metavar="US",
        help="Arm the §24 bounded busy-poll lever (STARWAY_BUSYPOLL_US): "
             "spin up to US microseconds after the last event before "
             "blocking; native engine only.",
    )
    p.add_argument(
        "--paired-baseline", action="store_true",
        help="Striped scenario only: interleave a striping-OFF baseline with "
             "every striping-ON iteration in ONE process/connection and "
             "report the per-pair ratio -- the box-noise-immune methodology "
             "from BENCHMARK.md, now built in.",
    )
    p.add_argument("--output", type=Path, help="Path to write the JSON report.")
    p.add_argument("--store-trace", action="store_true", help="Include per-iteration samples in the report.")
    p.add_argument(
        "--trace", type=Path, metavar="PATH",
        help="Enable swtrace (STARWAY_TRACE=1) for the run and write a "
             "Chrome trace_event JSON here (open in Perfetto); the printed "
             "report gains a p-tile stage breakdown.",
    )
    p.add_argument(
        "--metrics", type=Path, metavar="PATH",
        help="Arm the swscope telemetry sampler (STARWAY_METRICS_PATH) for "
             "the run, appending JSONL samples here; the JSON report gains "
             "a 'telemetry' time-series summary (peak/mean queue depth, "
             "journal high-water).  View with python -m starway_tpu.metrics.",
    )
    return p


_OVERRIDE_KEYS = {
    "large-array": [("large_bytes", "message_bytes"), ("large_iterations", "iterations"), ("large_warmup", "warmup")],
    "small-messages": [
        ("small_bytes", "message_bytes"), ("small_iterations", "iterations"),
        ("small_warmup", "warmup_batches"), ("small_concurrency", "concurrency"),
    ],
    "pingpong-flag": [("flag_iterations", "iterations"), ("flag_warmup", "warmup")],
    "streaming-duplex": [("stream_bytes", "message_bytes"), ("stream_iterations", "iterations"), ("stream_warmup", "warmup")],
    "striped": [("striped_bytes", "message_bytes"), ("striped_iterations", "iterations"), ("striped_warmup", "warmup")],
    "flooded": [("flood_bytes", "message_bytes"), ("flood_messages", "messages"), ("flood_iterations", "iterations")],
    "reshard": [
        ("reshard_bytes", "message_bytes"), ("reshard_blocks", "blocks"),
        ("reshard_iterations", "iterations"), ("reshard_warmup", "warmup"),
    ],
}


def scenario_plan(args: argparse.Namespace) -> list[tuple[str, dict[str, Any]]]:
    from .benchmarks import list_scenarios
    from .benchmarks.scenarios import SCENARIOS

    requested: Sequence[str]
    if not args.scenarios or (len(args.scenarios) == 1 and args.scenarios[0].lower() == "all"):
        requested = list_scenarios()
    else:
        requested = args.scenarios
    plan = []
    for name in requested:
        if name not in SCENARIOS:
            raise ValueError(f"Unknown scenario '{name}'. Available: {', '.join(list_scenarios())}")
        overrides = {}
        for arg_name, cfg_key in _OVERRIDE_KEYS.get(name, []):
            val = getattr(args, arg_name, None)
            if val is not None:
                overrides[cfg_key] = val
        if getattr(args, "payload", None) and name in ("large-array", "streaming-duplex"):
            overrides["payload"] = args.payload
        if name in ("striped", "flooded") and getattr(args, "paired_baseline",
                                                     False):
            overrides["paired"] = True
        plan.append((name, overrides))
    return plan


class ClientSideContext:
    """What scenarios see on the measuring side."""

    def __init__(self, client):
        from .benchmarks.scenarios import TAG_MASK

        self.client = client
        self.tag_mask = TAG_MASK
        self._ready = np.zeros(1, dtype=np.uint8)
        self._done = np.zeros(1, dtype=np.uint8)

    async def send_control(self, payload: Mapping[str, Any]) -> None:
        from .benchmarks.scenarios import CONTROL_TAG

        await self.client.asend(_encode_ctl(payload), CONTROL_TAG)
        await self.flush()

    async def wait_ready(self) -> None:
        from .benchmarks.scenarios import READY_TAG

        await self.client.arecv(self._ready, READY_TAG, self.tag_mask)

    async def wait_done(self) -> None:
        from .benchmarks.scenarios import DONE_TAG

        await self.client.arecv(self._done, DONE_TAG, self.tag_mask)

    async def flush(self) -> None:
        await self.client.aflush()


class ServerSideContext:
    """What scenarios see on the echo/sink side."""

    def __init__(self, server, endpoint):
        from .benchmarks.scenarios import TAG_MASK

        self.server = server
        self.endpoint = endpoint
        self.tag_mask = TAG_MASK

    async def recv_control(self, max_bytes: int = 4096) -> dict:
        from .benchmarks.scenarios import CONTROL_TAG

        buf = np.empty(max_bytes, dtype=np.uint8)
        _, length = await self.server.arecv(buf, CONTROL_TAG, self.tag_mask)
        return _decode_ctl(buf, length)

    async def signal_ready(self) -> None:
        from .benchmarks.scenarios import READY_TAG

        await self.server.asend(self.endpoint, np.ones(1, dtype=np.uint8), READY_TAG)

    async def signal_done(self) -> None:
        from .benchmarks.scenarios import DONE_TAG

        await self.server.asend(self.endpoint, np.ones(1, dtype=np.uint8), DONE_TAG)

    async def flush_endpoint(self) -> None:
        await self.server.aflush_ep(self.endpoint)


async def run_client(args: argparse.Namespace) -> list:
    from . import Client
    from .benchmarks import get_scenario

    client = Client()
    results = []
    try:
        if args.connect_mode == "worker":
            if not args.worker_address:
                raise ValueError("--worker-address required for connect-mode=worker")
            blob = parse_worker_address(args.worker_address)
            await client.aconnect_address(blob)
            print(f"[client] Connected via worker address ({len(blob)} bytes).")
        else:
            await client.aconnect(args.server_host, args.port)
            print(f"[client] Connected to {args.server_host}:{args.port}.")

        ctx = ClientSideContext(client)
        for name, overrides in scenario_plan(args):
            print(f"[client] Starting scenario '{name}' with overrides {overrides or 'defaults'}.")
            await ctx.send_control({"scenario": name, "config": overrides})
            await ctx.wait_ready()
            result = await get_scenario(name).run_client(ctx, overrides)
            results.append(result)
            await ctx.wait_done()
            print(f"[client] Completed '{name}'.")
        try:
            await ctx.send_control({"scenario": "__shutdown__"})
            await ctx.flush()
        except Exception:
            # The server closes the moment it sees the shutdown frame, so the
            # flush ACK legitimately races the peer's close; a reset here
            # means the frame arrived (or the peer died — either way, done).
            pass
    finally:
        try:
            await client.aclose()
        except Exception:
            pass  # close-before-connect must not mask the original error
    return results


async def run_server(args: argparse.Namespace, address_publish: "asyncio.Future | None" = None) -> None:
    from . import Server
    from .benchmarks import get_scenario
    from .benchmarks.scenarios import SCENARIOS

    server = Server()
    loop = asyncio.get_running_loop()
    accepted: asyncio.Queue = asyncio.Queue()
    server.set_accept_cb(lambda ep: loop.call_soon_threadsafe(accepted.put_nowait, ep))

    if args.listen_mode == "worker":
        blob = server.listen_address()
        print(f"[server] Listening via worker address: {blob.hex()}")
        if address_publish is not None and not address_publish.done():
            address_publish.set_result(blob)
    else:
        server.listen(args.addr, args.port)
        print(f"[server] Listening on {args.addr}:{args.port}")
        if address_publish is not None and not address_publish.done():
            address_publish.set_result(None)

    endpoint = await accepted.get()
    print("[server] Client accepted.")
    ctx = ServerSideContext(server, endpoint)
    try:
        while True:
            control = await ctx.recv_control()
            name = control.get("scenario")
            if name == "__shutdown__":
                print("[server] Shutdown request received.")
                break
            if name not in SCENARIOS:
                raise ValueError(f"Unknown scenario '{name}' from client.")
            overrides = control.get("config", {})
            print(f"[server] Running scenario '{name}'.")
            await get_scenario(name).run_server(ctx, overrides)
            await ctx.signal_done()
            print(f"[server] Scenario '{name}' completed.")
    finally:
        await server.aclose()
        print("[server] Closed.")


async def run_loopback(args: argparse.Namespace) -> list:
    """Single-process client+server, the cheapest distributed simulation
    (reference: bench.py:359-381).  In worker listen mode the runtime-minted
    address blob is wired to the client automatically."""
    addr_fut: asyncio.Future = asyncio.get_running_loop().create_future()
    server_task = asyncio.create_task(run_server(args, addr_fut))

    # A server that dies before (or while) the client is running must fail
    # the loopback, not hang it: before this guard, an exception raised in
    # run_server prior to resolving addr_fut (e.g. an ImportError) left the
    # `await addr_fut` below pending forever.
    def _server_done(t: asyncio.Task) -> None:
        if addr_fut.done() or t.cancelled():
            return
        exc = t.exception()
        addr_fut.set_exception(
            exc if exc is not None
            else RuntimeError("bench server exited before listening"))

    server_task.add_done_callback(_server_done)

    client_task = None
    try:
        blob = await addr_fut
        if blob is not None:
            args.connect_mode = "worker"
            args.worker_address = blob.hex()
        client_task = asyncio.create_task(run_client(args))
        done, _ = await asyncio.wait(
            {client_task, server_task}, return_when=asyncio.FIRST_COMPLETED)
        if client_task not in done:
            # Surface a server FAILURE immediately (otherwise the client
            # would hang on a dead peer).  A clean server exit is normal
            # here: it means the client's __shutdown__ was processed and
            # the client is wrapping up -- keep waiting for its results.
            server_task.result()
        results = await client_task
        await server_task  # late server errors still surface
        return results
    except BaseException:
        for t in (client_task, server_task):
            if t is not None:
                t.cancel()
        for t in (client_task, server_task):
            if t is not None:
                try:
                    await t
                except BaseException:
                    pass
        raise


def _dump_trace(args: argparse.Namespace) -> "dict | None":
    """Write the Chrome trace for --trace runs and print the p-tile stage
    breakdown from the recorded EV_STAGE spans.  Returns the ring dumps'
    per-stage p-tiles for the JSON report (None when --trace is off)."""
    from . import trace as trace_mod
    from .core import swtrace
    from .perf import percentile as _percentile

    dumps = swtrace.dump_all()
    path = trace_mod.write_chrome(dumps, args.trace)
    n_events = sum(len(d["events"]) for d in dumps)
    print(f"\nChrome trace written to {path} ({n_events} events, "
          f"{len(dumps)} worker(s)); open in Perfetto or chrome://tracing")
    durs: dict[str, list] = {}
    for dump in dumps:
        for ev in dump["events"]:
            if ev[1] == swtrace.EV_STAGE and ev[6] > 0:
                durs.setdefault(ev[5], []).append(ev[6])
    if not durs:
        # Stage spans are recorded by the Python data plane; a pure native
        # run still gets op spans, just no stage breakdown.
        return None
    print("[stage p-tiles] (us per recorded span; stage=D2H tx/rx=transport "
          "place=H2D)")
    ptiles = {}
    for name in sorted(durs):
        xs = sorted(durs[name])
        p50, p90, p99 = (_percentile(xs, 50) * 1e6, _percentile(xs, 90) * 1e6,
                         _percentile(xs, 99) * 1e6)
        ptiles[name] = {"count": len(xs), "p50_us": p50, "p90_us": p90,
                        "p99_us": p99}
        print(f"  {name}: n={len(xs)} p50={p50:.1f}us p90={p90:.1f}us "
              f"p99={p99:.1f}us")
    return ptiles


def active_levers() -> list:
    """The §24 swfast levers armed for this process, by env (covers both
    the CLI flags and direct env arming) -- recorded in every JSON report
    so a result row is self-describing."""
    levers = []
    if os.environ.get("STARWAY_IOURING") == "1":
        levers.append("uring")
    if os.environ.get("STARWAY_ZEROCOPY") == "1":
        levers.append("zerocopy")
    try:
        if int(os.environ.get("STARWAY_BUSYPOLL_US", "0")) > 0:
            levers.append(f"busypoll:{int(os.environ['STARWAY_BUSYPOLL_US'])}")
    except ValueError:
        pass
    return levers


def dump_results(results, args: argparse.Namespace) -> None:
    from . import perf
    from .benchmarks import get_scenario

    if not results:
        print("No results collected.")
        return
    print("\n=== Benchmark Results ===")
    for result in results:
        print(f"\n[{result.name}] {get_scenario(result.name).description}")
        for key, value in result.metrics.items():
            print(f"  {key}: {value:.6f}" if isinstance(value, float) else f"  {key}: {value}")
    stages = perf.stage_snapshot()
    if stages:
        print("\n[pipeline stages] (this process, whole run; "
              "stage=D2H tx/rx=transport place=H2D)")
        for name, s in sorted(stages.items()):
            avg_us = s["seconds"] / s["count"] * 1e6 if s["count"] else 0.0
            print(f"  {name}: n={s['count']} avg={avg_us:.1f}us "
                  f"bytes={s['bytes']} ({s['gbps']:.2f} GB/s)")
    stage_ptiles = _dump_trace(args) if args.trace else None
    if args.output:
        args.output.parent.mkdir(parents=True, exist_ok=True)
        report = {
            "timestamp": time.time(),
            "transport": os.environ.get("STARWAY_TLS"),
            # §24: which swfast levers this run armed ([] = seed path).
            "levers": active_levers(),
            "scenarios": [r.to_dict(include_samples=args.store_trace) for r in results],
            # Per-stage pipeline telemetry (DESIGN.md §12): loopback runs
            # see both sides; client-role runs see the client's half.
            "stages": stages,
        }
        if args.trace:
            report["trace"] = str(args.trace)
            if stage_ptiles:
                report["stage_ptiles"] = stage_ptiles
        if args.metrics:
            report["metrics"] = str(args.metrics)
            report["telemetry"] = _telemetry_summary()
        args.output.write_text(json.dumps(report, indent=2))
        print(f"\nJSON results written to {args.output}")
    elif args.metrics:
        _telemetry_summary()


def _telemetry_summary() -> dict:
    """Close the --metrics run: one final sample (so the last counter
    deltas land in the JSONL) and the whole-run gauge summary."""
    from .core import telemetry

    telemetry.sample_now()
    summary = telemetry.summarize(
        telemetry.recent_samples(config_metrics_window()))
    print(f"[telemetry] {summary['samples']} sample(s); peak tx queue depth "
          f"{summary['peak_tx_queue_depth']}, peak journal bytes "
          f"{summary['peak_journal_bytes']}")
    return summary


def config_metrics_window() -> int:
    from . import config

    return config.metrics_ring_size()


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.tls:
        os.environ["STARWAY_TLS"] = args.tls
    if args.rails:
        # Rails negotiate at connect, so the env must be set before any
        # worker is built; the threshold default arms striping for the
        # 'striped' scenario's >= 1 MiB messages.
        os.environ["STARWAY_RAILS"] = str(max(1, args.rails))
        os.environ.setdefault("STARWAY_STRIPE_THRESHOLD", str(1 << 20))
    if args.fc_window:
        # Flow control negotiates at connect too (the "fc" handshake key).
        os.environ["STARWAY_FC_WINDOW"] = str(args.fc_window)
    # §24 swfast levers: engine-local (no handshake surface), but sampled
    # once at worker start -- so land the envs before any worker exists.
    if args.uring:
        os.environ["STARWAY_IOURING"] = "1"
    if args.zerocopy:
        os.environ["STARWAY_ZEROCOPY"] = "1"
    if args.busypoll:
        os.environ["STARWAY_BUSYPOLL_US"] = str(max(0, args.busypoll))
    if args.trace:
        # Must land before any worker is created: rings are armed per
        # worker at construction (core/swtrace.py).
        os.environ["STARWAY_TRACE"] = "1"
    if args.metrics:
        # Same construction-time rule for the sampler registry
        # (core/telemetry.py register_worker).  Start the file fresh:
        # the emitter appends, and stale samples from an earlier run
        # would break the per-process mono ordering consumers assert.
        try:
            args.metrics.unlink()
        except OSError:
            pass
        os.environ["STARWAY_METRICS_PATH"] = str(args.metrics)
        os.environ.setdefault("STARWAY_METRICS_INTERVAL", "0.25")
    if getattr(args, "payload", None) == "device":
        # devpull is only advertised in the handshake once the jax backend
        # is up (the handshake never initialises one); device-payload runs
        # should measure the pull path, so bring it up before connecting.
        import jax

        jax.devices()

    if args.role == "server":
        asyncio.run(run_server(args))
        return 0
    if args.role == "client":
        results = asyncio.run(run_client(args))
        dump_results(results, args)
        return 0
    if args.role == "loopback":
        results = asyncio.run(run_loopback(args))
        dump_results(results, args)
        return 0
    raise ValueError(f"Unknown role {args.role}")


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
