"""Test-support utilities shipped with the package.

:mod:`starway_tpu.testing.faults` -- the TCP fault-injection proxy fabric
used by tests/test_faults.py (and usable by embedders to chaos-test their
own deployments).
"""

from .faults import FaultProxy  # noqa: F401
