"""TCP fault-injection proxy fabric.

Production communication stacks treat partial failure as a first-class
input: connections that die mid-frame, peers that accept and then go
silent, links that partition without an RST.  The reference repo never
exercises any of these (its failure tests kill whole processes); this
module makes them reproducible on loopback so the fault-tolerance layer
(deadlines, keepalive liveness, reconnect -- see DESIGN.md "Failure
semantics & deadlines") can be driven through real sockets in-process.

:class:`FaultProxy` sits between a starway client and server::

    server.listen("127.0.0.1", sport)
    proxy = FaultProxy("127.0.0.1", sport)        # transparent forwarder
    proxy.start()
    await client.aconnect("127.0.0.1", proxy.port)
    ...
    proxy.partition()   # both directions go silent; sockets stay open

Fault modes (constructor ``mode=``):

``forward``
    Transparent byte pump (the default).  Runtime faults are injected
    with :meth:`partition` / :meth:`heal`.
``delay``
    Forward with ``delay`` seconds of added latency per chunk.
``drop``
    Forward ``limit_bytes`` of client->server traffic, then hard-kill both
    sides with an RST (SO_LINGER 0) -- the mid-frame connection kill.
``truncate``
    Forward ``limit_bytes`` of client->server traffic, then FIN both
    sides -- the peer observes a clean EOF in the middle of a frame.
``blackhole``
    Accept the client, never dial the target, read and discard inbound
    bytes, send nothing -- the accept-then-silence failure (a wedged or
    firewalled peer).
``choke``
    Accept and forward, but drain client->server traffic at
    ``rate_bytes_per_s`` (small reads + proportional sleeps; the return
    path stays a transparent pipe).  The reproducible slow consumer:
    overload tests (DESIGN.md §18 flow control, bounded unexpected
    queues, deadline shedding) get a receiver that genuinely cannot keep
    up without real slow hardware or test-side sleeps.
``duplicate``
    Frame-aware c->s forwarding that sends every *sequenced* session unit
    (T_SEQ prefix + its frame, core/frames.py) past ``limit_bytes``
    TWICE -- the replayed-frame overlap a resilient-session receiver must
    drop by sequence number (``dup_frames_dropped``).  Handshake and
    unsequenced frames pass through untouched, so the mode is a no-op on
    seed-parity conns (they carry no T_SEQ frames at all).
``reorder``
    Frame-aware c->s forwarding that swaps ONE adjacent pair of sequenced
    units past ``limit_bytes`` (then forwards transparently).  The
    receiver sees a sequence gap it cannot repair in place, resets the
    conn, and the session layer's redial + replay-from-cumulative-ACK
    path runs end to end.
``corrupt``
    Frame-aware c->s forwarding that mutates matching units past
    ``limit_bytes`` -- the silent-data-corruption generator the §19
    integrity plane (``STARWAY_INTEGRITY``, DESIGN.md §19) is tested
    against.  Selection and mutation knobs:

    * ``corrupt_ftype`` -- wire frame type to target (e.g. 3 = DATA,
      12 = SDATA); ``None`` targets any frame that carries a body.
    * ``corrupt_where`` -- ``"payload"`` (default) flips inside the
      frame's body (for SDATA: past the 24-byte sub-header, so routing
      stays intact and the receiver answers T_SNACK); ``"header"`` flips
      inside the 17-byte header / sub-header region (routing corrupt:
      the receiver must poison the conn).
    * ``corrupt_kind`` -- ``"flip"`` (default) XORs one byte at
      ``corrupt_offset`` (relative to the chosen region; default mid);
      ``"truncate"`` deletes ``corrupt_bytes`` bytes there instead,
      desyncing the stream mid-frame.
    * ``corrupt_count`` -- units to mutate (default 1, then the pump is
      transparent again).

    Without integrity negotiated the corruption is SILENT -- bytes
    deliver as good data -- which is exactly the blindness the plane
    exists to remove.

``partition_after`` (bytes, any mode that forwards) auto-triggers
:meth:`partition` once that much client->server traffic has passed --
deterministic mid-stream silence without test-side sleeps.
:meth:`reset_mid_message` arms a byte-exact RST: the proxy forwards
client->server traffic up to an absolute byte offset (splitting a chunk
if needed, so the kill really lands mid-frame) and then hard-kills both
sides -- the deterministic connection-death-mid-transfer the session
resume tests are built on.

Threads: one acceptor plus two pumps per proxied connection, all daemons;
:meth:`stop` closes every socket and joins.  Loopback-only by design --
this is a test harness, not a production relay.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

_CHUNK = 1 << 16

MODES = ("forward", "delay", "drop", "truncate", "blackhole", "duplicate",
         "reorder", "choke", "corrupt")

# Wire-format knowledge for the frame-aware modes (core/frames.py): 17-byte
# little-endian header {u8 type, u64 a, u64 b}; HELLO/HELLO_ACK/DATA/DEVPULL
# stream `b` payload bytes behind the header, everything else is bare.  A
# T_SEQ frame (9) is the session layer's sequence prefix and a T_CSUM
# frame (17) the §19 integrity prefix; both travel glued to the frame they
# announce -- the frame-aware modes treat [SEQ][CSUM][frame] as one unit.
_HDR = 17
_T_SEQ = 9
_T_SDATA = 12  # striped chunk: self-describing, dup/reorder-eligible
_T_CSUM = 17   # §19 integrity prefix: glues to the next frame
_PREFIX_TYPES = frozenset((_T_SEQ, _T_CSUM))
_SDATA_SUB = 24  # stripe sub-header behind an SDATA header (frames.py)
_BODY_TYPES = frozenset((1, 2, 3, 6, 12))  # HELLO, HELLO_ACK, DATA, DEVPULL, SDATA


class _ConnPair:
    """One proxied connection: the client-side socket and (unless
    blackholed) the upstream socket to the real server."""

    def __init__(self, downstream: socket.socket, upstream: Optional[socket.socket]):
        self.down = downstream
        self.up = upstream
        self.dead = False

    def kill(self, rst: bool) -> None:
        if self.dead:
            return
        self.dead = True
        for s in (self.down, self.up):
            if s is None:
                continue
            try:
                if rst:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                 struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                # shutdown() interrupts a pump thread blocked in recv();
                # close() alone does not and would strand it until the
                # join timeout.
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class FaultProxy:
    def __init__(self, target_host: str, target_port: int, mode: str = "forward",
                 *, listen_host: str = "127.0.0.1", delay: float = 0.0,
                 limit_bytes: int = 0, partition_after: Optional[int] = None,
                 rate_bytes_per_s: int = 64 * 1024,
                 corrupt_ftype: Optional[int] = None,
                 corrupt_where: str = "payload",
                 corrupt_kind: str = "flip",
                 corrupt_offset: Optional[int] = None,
                 corrupt_bytes: int = 1,
                 corrupt_count: int = 1):
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r}; expected one of {MODES}")
        if corrupt_where not in ("payload", "header"):
            raise ValueError(f"corrupt_where {corrupt_where!r}")
        if corrupt_kind not in ("flip", "truncate"):
            raise ValueError(f"corrupt_kind {corrupt_kind!r}")
        self.target = (target_host, target_port)
        self.mode = mode
        self.delay = delay
        self.rate = max(1, int(rate_bytes_per_s))
        self.limit_bytes = limit_bytes
        self.partition_after = partition_after
        self.corrupt_ftype = corrupt_ftype
        self.corrupt_where = corrupt_where
        self.corrupt_kind = corrupt_kind
        self.corrupt_offset = corrupt_offset
        self.corrupt_bytes = max(1, int(corrupt_bytes))
        self._corrupt_left = max(0, int(corrupt_count))
        self.corrupted_units = 0  # units actually mutated (test oracle)
        self._partitioned = threading.Event()
        self._stalled = threading.Event()
        self._stopping = threading.Event()
        self._lock = threading.Lock()
        self._pairs: list[_ConnPair] = []
        self._threads: list[threading.Thread] = []
        self._c2s_bytes = 0  # client->server bytes forwarded (fault triggers)
        self._reset_at: Optional[int] = None  # armed byte-exact RST offset
        self._reordered = False  # reorder mode fires its one swap only once
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(64)
        self.port: int = self._listener.getsockname()[1]

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FaultProxy":
        t = threading.Thread(target=self._accept_loop, name="faultproxy-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def __enter__(self) -> "FaultProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stopping.set()
        self._stalled.clear()  # release pumps parked in the stall loop
        try:
            self._listener.shutdown(socket.SHUT_RDWR)  # wake a blocked accept
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            pairs = list(self._pairs)
        for p in pairs:
            p.kill(rst=False)
        for t in self._threads:
            t.join(timeout=5)

    # ------------------------------------------------------ runtime faults
    def partition(self) -> None:
        """Go silent in both directions.  Sockets stay open: neither peer
        sees EOF or RST -- the network-partition / wedged-peer failure that
        only deadlines or keepalive liveness can detect."""
        self._partitioned.set()

    def heal(self) -> None:
        """Resume forwarding.  Bytes swallowed during the partition are
        gone (this is a byte pipe, not a retransmitting relay), so healing
        mid-message leaves the framed stream corrupt -- heal only between
        messages, or expect the engines to declare the conn broken."""
        self._partitioned.clear()

    def stall(self) -> None:
        """Stop READING from both sides (unlike :meth:`partition`, which
        keeps draining and discarding).  Kernel buffers back up and the
        peers' sockets wedge -- the backpressure failure that blocks even
        a send's first byte."""
        self._stalled.set()

    def unstall(self) -> None:
        self._stalled.clear()

    def kill_all(self, rst: bool = True) -> None:
        """Tear down every proxied connection now (RST by default)."""
        with self._lock:
            pairs = list(self._pairs)
        for p in pairs:
            p.kill(rst)

    def reset_mid_message(self, at_bytes: int) -> None:
        """Arm a byte-exact connection kill: forward client->server bytes
        up to absolute offset ``at_bytes`` (splitting the chunk that
        crosses it, so the RST genuinely lands mid-frame) then hard-kill
        both sides.  Single-shot: a reconnecting session pair pumps
        through undisturbed afterwards -- the deterministic
        death-mid-transfer the resume tests are built on."""
        self._reset_at = at_bytes

    @property
    def forwarded_bytes(self) -> int:
        return self._c2s_bytes

    # ------------------------------------------------------------ internals
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                down, _ = self._listener.accept()
            except OSError:
                return
            down.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.mode == "blackhole":
                pair = _ConnPair(down, None)
                with self._lock:
                    self._pairs.append(pair)
                t = threading.Thread(target=self._blackhole_loop, args=(pair,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
                continue
            try:
                up = socket.create_connection(self.target, timeout=5)
                up.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                down.close()
                continue
            pair = _ConnPair(down, up)
            with self._lock:
                self._pairs.append(pair)
            for src, dst, is_c2s in ((down, up, True), (up, down, False)):
                # duplicate/reorder/corrupt are frame-aware on the faulted
                # (c->s) direction only; the return path stays a byte pipe.
                fn = (self._pump_framed
                      if is_c2s and self.mode in ("duplicate", "reorder",
                                                  "corrupt")
                      else self._pump)
                t = threading.Thread(target=fn, args=(pair, src, dst, is_c2s),
                                     daemon=True)
                t.start()
                self._threads.append(t)

    def _blackhole_loop(self, pair: _ConnPair) -> None:
        # Accept-then-silence: drain inbound (so the client's kernel buffer
        # never backs up into a send-side signal), respond with nothing.
        while not self._stopping.is_set() and not pair.dead:
            try:
                if not pair.down.recv(_CHUNK):
                    break
            except OSError:
                break
        pair.kill(rst=False)

    def _pump(self, pair: _ConnPair, src: socket.socket, dst: socket.socket,
              is_c2s: bool) -> None:
        # choke (c->s only): small reads so the rate limit has fine
        # granularity; the proportional sleep after each forward is what
        # makes the drain rate real.
        choked = is_c2s and self.mode == "choke"
        chunk = min(_CHUNK, max(256, self.rate // 20)) if choked else _CHUNK
        while not self._stopping.is_set() and not pair.dead:
            while (self._stalled.is_set() and not self._stopping.is_set()
                   and not pair.dead):
                time.sleep(0.01)  # backpressure: let kernel buffers fill
            try:
                data = src.recv(chunk)
            except OSError:
                # One side died hard (RST): propagate to the other, as a
                # direct connection would -- a silent exit here would
                # leave the survivor connected to a dead pipe forever.
                if not self._partitioned.is_set():
                    pair.kill(rst=True)
                return
            # A pump already parked in recv() when stall() fired still
            # returns this chunk: HOLD it (don't forward, don't drop)
            # until unstalled, so the stall is byte-deterministic -- no
            # in-flight frame slips past the wedge.
            while (self._stalled.is_set() and not self._stopping.is_set()
                   and not pair.dead):
                time.sleep(0.01)
            if not data:
                if self._partitioned.is_set():
                    return  # a partition swallows EOFs too: pure silence
                # Clean EOF from one side: half-close towards the other so
                # graceful shutdowns still look graceful through the proxy.
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                return
            if self._partitioned.is_set():
                continue  # swallowed: silence, not EOF
            if self.delay > 0:
                time.sleep(self.delay)
            if choked:
                time.sleep(len(data) / self.rate)
            if is_c2s and self._reset_at is not None:
                remaining = self._reset_at - self._c2s_bytes
                if len(data) >= remaining:
                    # Deliver exactly up to the armed offset, then RST:
                    # the kill lands mid-frame, byte-deterministically.
                    self._reset_at = None
                    if remaining > 0:
                        self._send_all(pair, dst, data[:remaining], is_c2s)
                    pair.kill(rst=True)
                    return
            if is_c2s and self.mode in ("drop", "truncate"):
                remaining = self.limit_bytes - self._c2s_bytes
                if remaining <= 0:
                    pair.kill(rst=self.mode == "drop")
                    return
                if len(data) > remaining:
                    data = data[:remaining]  # deliver the partial frame...
                    if not self._send_all(pair, dst, data, is_c2s):
                        return
                    pair.kill(rst=self.mode == "drop")  # ...then the fault
                    return
            if not self._send_all(pair, dst, data, is_c2s):
                return
            if (is_c2s and self.partition_after is not None
                    and self._c2s_bytes >= self.partition_after):
                self._partitioned.set()

    def _maybe_corrupt(self, unit: bytes, plen: int, ftype: int) -> bytes:
        """Corrupt-mode mutation of one assembled unit.  ``plen`` is the
        byte length of the glued SEQ/CSUM prefixes; the targeted frame's
        header starts there.  Mutates at most ``corrupt_count`` units."""
        if self._corrupt_left <= 0:
            return unit
        if self.corrupt_ftype is not None:
            if ftype != self.corrupt_ftype:
                return unit
        elif ftype not in (3, 6, 12):  # DATA / DEVPULL / SDATA
            return unit
        head_len = _HDR + (_SDATA_SUB if ftype == _T_SDATA else 0)
        if self.corrupt_where == "header":
            start, length = plen, min(head_len, len(unit) - plen)
        else:
            start = plen + head_len
            length = len(unit) - start
        if length <= 0:
            return unit
        rel = self.corrupt_offset if self.corrupt_offset is not None \
            else length // 2
        idx = start + max(0, min(length - 1, rel))
        out = bytearray(unit)
        if self.corrupt_kind == "flip":
            out[idx] ^= 0x20
        else:  # truncate: drop bytes mid-frame, desyncing the stream
            del out[idx : idx + self.corrupt_bytes]
        self._corrupt_left -= 1
        self.corrupted_units += 1
        return bytes(out)

    def _pump_framed(self, pair: _ConnPair, src: socket.socket,
                     dst: socket.socket, is_c2s: bool) -> None:
        """Frame-aware client->server pump for the duplicate/reorder/
        corrupt modes: reassembles the byte stream into wire units
        (header + payload, with T_SEQ/T_CSUM prefixes glued to the frame
        they announce) and injects the fault on eligible units past
        ``limit_bytes``.  Other traffic (handshake, liveness, ACKs)
        passes through untouched, so seed-parity conns see a transparent
        proxy."""
        buf = bytearray()
        held: list = []   # SEQ/CSUM prefix units awaiting their frame
        reorder_hold: Optional[bytes] = None
        try:
            src.settimeout(0.2)  # idle tick: a held swap must not hang a quiet stream
        except OSError:
            pass
        while not self._stopping.is_set() and not pair.dead:
            while (self._stalled.is_set() and not self._stopping.is_set()
                   and not pair.dead):
                time.sleep(0.01)
            try:
                data = src.recv(_CHUNK)
            except socket.timeout:
                if reorder_hold is not None:
                    # Nothing followed the held unit: flush it (the swap
                    # degenerates to a delay) so a trailing barrier frame
                    # cannot wedge the stream.
                    unit, reorder_hold = reorder_hold, None
                    if not self._forward_unit(pair, dst, unit, is_c2s):
                        return
                continue
            except OSError:
                # RST propagation, like the raw pump above.
                if not self._partitioned.is_set():
                    pair.kill(rst=True)
                return
            # Hold-not-forward on a stall that landed mid-recv, like the
            # raw pump above.
            while (self._stalled.is_set() and not self._stopping.is_set()
                   and not pair.dead):
                time.sleep(0.01)
            if not data:
                if self._partitioned.is_set():
                    return
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                return
            if self._partitioned.is_set():
                continue
            buf += data
            while True:
                if len(buf) < _HDR:
                    break
                ftype = buf[0]
                blen = struct.unpack_from("<Q", buf, 9)[0]
                need = _HDR + (blen if ftype in _BODY_TYPES else 0)
                if len(buf) < need:
                    break
                unit = bytes(buf[:need])
                del buf[:need]
                if ftype in _PREFIX_TYPES:
                    held.append(unit)  # glue to the frame they announce
                    continue
                # dup/reorder eligibility: sequenced session units, or
                # self-describing striped chunks (offset-dedup'd,
                # DESIGN.md §17) -- the faults these modes exercise.
                sequenced = (any(u[0] == _T_SEQ for u in held)
                             or ftype == _T_SDATA)
                plen = sum(len(u) for u in held)
                if held:
                    unit = b"".join(held) + unit
                    held.clear()
                out = unit
                past = self._c2s_bytes >= self.limit_bytes
                if past and self.mode == "corrupt":
                    out = self._maybe_corrupt(unit, plen, ftype)
                elif sequenced and past and self.mode == "duplicate":
                    out = unit + unit  # replay overlap: receiver must dedup
                elif (sequenced and past and self.mode == "reorder"
                      and not self._reordered):
                    if reorder_hold is None:
                        reorder_hold = unit
                        continue  # hold; the NEXT sequenced unit goes first
                    out = unit + reorder_hold
                    reorder_hold = None
                    self._reordered = True
                if not self._forward_unit(pair, dst, out, is_c2s):
                    return

    def _forward_unit(self, pair: _ConnPair, dst: socket.socket, out: bytes,
                      is_c2s: bool) -> bool:
        """Forward one (possibly duplicated/swapped) wire unit from the
        framed pump, honouring the byte-level triggers the raw pump also
        implements: an armed :meth:`reset_mid_message` offset splits the
        unit so the RST lands byte-exactly, and ``partition_after``
        swallows everything past its threshold."""
        if self._reset_at is not None:
            remaining = self._reset_at - self._c2s_bytes
            if len(out) >= remaining:
                self._reset_at = None
                if remaining > 0:
                    self._send_all(pair, dst, out[:remaining], is_c2s)
                pair.kill(rst=True)
                return False
        if not self._send_all(pair, dst, out, is_c2s):
            return False
        if (self.partition_after is not None
                and self._c2s_bytes >= self.partition_after):
            self._partitioned.set()
        return True

    def _send_all(self, pair: _ConnPair, dst: socket.socket, data: bytes,
                  is_c2s: bool) -> bool:
        try:
            dst.sendall(data)
        except OSError:
            pair.kill(rst=False)
            return False
        if is_c2s:
            self._c2s_bytes += len(data)
        return True
