"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context strategy next to ring attention (SURVEY.md section 5
names both: "Ulysses = all-to-all composed from P2P").  Where the ring keeps
queries resident and rotates kv, Ulysses re-shards: an all-to-all over the
sequence axis converts [heads: full, seq: sharded] into [heads: sharded,
seq: full], attention runs locally over the whole sequence, and a reverse
all-to-all restores the layout.  Two collectives total per attention call --
cheaper than a ring when n_heads >= mesh axis size and sequence length
dominates; the ring wins for GQA models with few kv heads.

Requires ``n_heads % axis_size == 0`` (and kv heads are pre-expanded when
grouped, since head shards must align).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import blockwise_attention, repeat_kv
from .sharding import shard_map_fn


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = True,
                      sm_scale: Optional[float] = None):
    """Per-device body (call inside shard_map): q/k/v are sequence shards
    ``[B, H, T_local, D]`` with the FULL head dimension; returns the local
    sequence shard of the output."""
    n = lax.axis_size(axis_name)
    if k.shape[1] != q.shape[1]:
        n_rep = q.shape[1] // k.shape[1]
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
    # [B, H, T/n, D] -> [B, H/n, T, D]: scatter heads, gather sequence.
    q2 = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k2 = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v2 = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    o2 = blockwise_attention(q2, k2, v2, causal=causal, sm_scale=sm_scale)
    # Restore: [B, H/n, T, D] -> [B, H, T/n, D].
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1, tiled=True)


def make_ulysses_attention(mesh, axis_name: str = "sp", *, causal: bool = True,
                           sm_scale: Optional[float] = None):
    """Jitted global-view Ulysses attention over sequence-sharded q/k/v."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return ulysses_attention(q, k, v, axis_name, causal=causal, sm_scale=sm_scale)

    return jax.jit(shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec))
