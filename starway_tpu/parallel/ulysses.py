"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context strategy next to ring attention (SURVEY.md section 5
names both: "Ulysses = all-to-all composed from P2P").  Where the ring keeps
queries resident and rotates kv, Ulysses re-shards: an all-to-all over the
sequence axis converts [heads: full, seq: sharded] into [heads: sharded,
seq: full], attention runs locally over the whole sequence, and a reverse
all-to-all restores the layout.  Two collectives total per attention call --
cheaper than a ring when n_heads >= mesh axis size and sequence length
dominates; the ring wins for GQA models with few kv heads.

Requires ``n_heads % axis_size == 0``.  Grouped kv stays narrow across the
all-to-all whenever ``n_kv_heads % axis_size == 0`` -- the collectives move
``1/n_rep`` of the expanded traffic and the expansion happens locally after
re-sharding (block-aligned head ranges keep the q-head -> kv-head mapping
exact); otherwise kv is pre-expanded so head shards align.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import blockwise_attention, repeat_kv
from .sharding import shard_map_fn


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      window: Optional[int] = None):
    """Per-device body (call inside shard_map): q/k/v are sequence shards
    ``[B, H, T_local, D]`` with the FULL head dimension; returns the local
    sequence shard of the output.

    ``window``: sliding-window band — after the re-shard each device holds
    the FULL sequence for its heads, so the band is just the local
    blockwise mask (no cross-shard bookkeeping, unlike the ring)."""
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    n = lax.axis_size(axis_name)
    n_rep = q.shape[1] // k.shape[1]
    if n_rep > 1 and k.shape[1] % n != 0:
        # Narrow heads don't split evenly over the axis: pre-expand.
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
        n_rep = 1
    # [B, H, T/n, D] -> [B, H/n, T, D]: scatter heads, gather sequence.
    # Grouped kv rides the all-to-all narrow (1/n_rep of the bytes): device
    # d ends up with q heads [d*H/n, (d+1)*H/n) and kv heads
    # [d*Hkv/n, (d+1)*Hkv/n), which are exactly each other's GQA partners
    # (q head h uses kv head h // n_rep), so the local repeat_kv below
    # reproduces the global mapping.
    q2 = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k2 = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v2 = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if jax.default_backend() == "tpu":
        # Same dispatch as models/llama.py:default_attn: the hand-tiled
        # flash kernel takes GROUPED (narrow) kv and, with a window,
        # DMA-elides out-of-band tiles — so windowed Ulysses wall-clock
        # scales with the band, matching the ring path.
        from ..ops.pallas_attention import flash_attention

        o2 = flash_attention(q2, k2, v2, causal=causal, sm_scale=sm_scale,
                             window=window)
    else:
        if n_rep > 1:
            k2 = repeat_kv(k2, n_rep)
            v2 = repeat_kv(v2, n_rep)
        o2 = blockwise_attention(q2, k2, v2, causal=causal,
                                 sm_scale=sm_scale, window=window)
    # Restore: [B, H/n, T, D] -> [B, H, T/n, D].
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1, tiled=True)


def make_ulysses_attention(mesh, axis_name: str = "sp", *, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           window: Optional[int] = None):
    """Jitted global-view Ulysses attention over sequence-sharded q/k/v.
    ``window``: sliding-window band (see :func:`ulysses_attention`)."""
    if window is not None and not causal:
        # Fail at build, not first-call trace (matches make_sharded_attn).
        raise ValueError("window requires causal attention")
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return ulysses_attention(q, k, v, axis_name, causal=causal,
                                 sm_scale=sm_scale, window=window)

    jitted = jax.jit(shard_map_fn(mesh, local, in_specs=(spec, spec, spec),
                                  out_specs=spec))
    if window is None:
        return jitted  # keep the PjitFunction surface (.lower, caching)

    def fn(q, k, v):
        return jitted(q, k, v)

    # resolve_attn_fn's windowed-config contract (models/llama.py);
    # attributes cannot be set on the jit object itself.
    fn.handles_window = True
    fn.window = window
    return fn
