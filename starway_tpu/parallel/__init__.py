"""Composition layer: parallelism patterns built on the device plane.

The reference ships only P2P primitives and documents the patterns users
build from them (SURVEY.md section 2 "Parallelism strategies": all-to-all
composed from P2P, DP-boundary transfers, ring neighbor exchange).  Here
those patterns are first-class, TPU-native:

* :mod:`sharding` -- mesh construction and NamedSharding helpers.
* :mod:`ring_attention` -- sequence-parallel attention over an ICI ring
  (CollectivePermute + online-softmax merge), the long-context substrate.
* :mod:`all_to_all` -- sharded KV-cache-style shuffles (BASELINE config 4).
* :mod:`dp_exchange` -- pytree activation/grad transfer between hosts over
  the async P2P API (BASELINE config 5).
* :mod:`fsdp` -- ZeRO-style fully-sharded params + optimizer state via
  GSPMD annotations (all-gather per use, reduce-scatter per grad).
* :mod:`pipeline` / :mod:`interleaved` -- collective 1F1B schedules over a
  ``pp`` ring (plain, and Megatron-style virtual chunks).
"""

from .fsdp import fsdp_specs, make_fsdp_train_step, shard_tree
from .interleaved import (
    build_interleaved_schedule,
    make_interleaved_pipeline_train,
)
from .sharding import make_mesh, mesh_sharding
from .ring_attention import (
    make_ring_attention,
    make_zigzag_ring_attention,
    ring_attention,
    zigzag_indices,
    zigzag_ring_attention,
)
from .all_to_all import make_shuffle
from .dp_exchange import ClientPort, ServerPort, recv_pytree, send_pytree

__all__ = [
    "make_mesh",
    "mesh_sharding",
    "fsdp_specs",
    "make_fsdp_train_step",
    "shard_tree",
    "ring_attention",
    "make_ring_attention",
    "build_interleaved_schedule",
    "make_interleaved_pipeline_train",
    "make_shuffle",
    "ClientPort",
    "ServerPort",
    "send_pytree",
    "recv_pytree",
]
