"""Sharded all-to-all shuffles: the KV-cache disaggregation pattern.

BASELINE config 4 ("1GB jax.Array all-to-all shuffle") built the TPU way: a
single jitted ``lax.all_to_all`` over the mesh axis, which XLA schedules as
an all-to-all over ICI -- versus the reference's composition of N^2 tagged
P2P sends (SURVEY.md section 2 checklist: "1GB all-to-all shuffle must be
composed from P2P").  A host-API composition equivalent lives in
examples/all_to_all_p2p.py for parity with that pattern.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ..ops.collectives import all_to_all
from .sharding import shard_map_fn


def make_shuffle(mesh, axis_name: str, *, split_axis: int = 1, concat_axis: int = 0):
    """Jitted resharding shuffle over ``axis_name``.

    The global view: input sharded on dim 0 over the axis; output is the
    transposed ownership -- dim ``split_axis`` becomes the sharded dim.  For
    a [S, B, ...] KV cache sharded on S, ``make_shuffle(mesh, "x")`` yields
    the cache sharded on B: every device sends 1/n of its shard to each
    peer, the disaggregated-serving handoff pattern.
    """

    def local(x):
        return all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis)

    in_spec = P(axis_name)
    out_spec_list = [None] * (max(split_axis, concat_axis) + 1)
    out_spec_list[split_axis] = axis_name
    out_spec = P(*out_spec_list)
    return jax.jit(shard_map_fn(mesh, local, in_specs=(in_spec,), out_specs=out_spec))
