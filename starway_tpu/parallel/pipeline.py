"""Pipeline parallelism: GPipe-style microbatch flow over a mesh axis.

The reference's nearest analogue is the streaming-duplex scenario
("simulate ... model parallelism, gradient + activation exchange",
benchmark.md:91-99).  Here the pattern is a real SPMD pipeline: each device
on the ``pp`` axis owns one stage's parameters; microbatches enter at stage
0, activations hop stage-to-stage with ``ppermute`` over ICI, and the last
stage emits outputs.  The schedule is the classic skewed loop: with S
stages and M microbatches the pipeline runs ``M + S - 1`` ticks, every
device computing on every tick once the pipe is full (bubble fraction
``(S-1)/(M+S-1)``).

This is the forward building block; paired with ``jax.vjp`` it extends to
1F1B-style training schedules.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .sharding import shard_map_fn


def pipeline_apply(stage_fn: Callable, stage_params, microbatches, axis_name: str):
    """Per-device body (call inside shard_map).

    ``stage_params``: this device's stage parameters (leading pp dim already
    sharded away by shard_map).  ``microbatches``: [M, mb, ...] -- the full
    microbatch stream (replicated; only stage 0 reads it).  Returns
    [M, mb, ...] outputs (valid on the last stage; other stages return
    zeros, letting the caller psum/gather as needed).
    """
    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = m + n - 1

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (while available); others take the
        # activation handed over from the previous stage.
        inject = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(stage == 0, inject, state)
        y = stage_fn(stage_params, x)
        # Hand activations down the pipe: stage i -> stage i+1.
        state_next = lax.ppermute(
            y, axis_name, [(i, (i + 1) % n) for i in range(n)]
        )
        # Last stage emits: its output for tick t corresponds to microbatch
        # t - (n - 1).
        out_idx = t - (n - 1)
        emit = (stage == n - 1) & (out_idx >= 0)
        outputs = lax.cond(
            emit,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        return (state_next, outputs), None

    init_state = jnp.zeros(mb_shape, microbatches.dtype)
    init_out = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    (_, outputs), _ = lax.scan(tick, (init_state, init_out), jnp.arange(ticks))
    return outputs


def make_pipeline(mesh, stage_fn: Callable, axis_name: str = "pp"):
    """Jitted global-view pipeline.

    ``stage_params`` global view: leading dim = number of stages, sharded
    over ``axis_name``.  ``microbatches`` replicated in; outputs returned
    sharded on the pp axis (only the last stage's shard is meaningful --
    sum over the axis with ``collect=True`` semantics handled by caller) --
    here we psum so every device returns the full outputs.
    """

    def local(stage_params, microbatches):
        out = pipeline_apply(stage_fn, stage_params, microbatches, axis_name)
        # Only the last stage holds real outputs; share them with everyone.
        return lax.psum(out, axis_name)

    return jax.jit(
        shard_map_fn(
            mesh,
            local,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
        )
    )
