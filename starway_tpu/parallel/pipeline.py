"""Pipeline parallelism: GPipe-style forward + a 1F1B training schedule.

The reference's nearest analogue is the streaming-duplex scenario
("simulate ... model parallelism, gradient + activation exchange",
/root/reference/benchmark.md:91-99).  Here the pattern is a real SPMD
pipeline: each device on the ``pp`` axis owns one stage's parameters;
microbatches enter at stage 0, activations hop stage-to-stage with
``ppermute`` over ICI, gradients hop back the other way.

Forward-only (``pipeline_apply``/``make_pipeline``): the classic skewed
loop — with S stages and M microbatches the pipeline runs ``M + S - 1``
ticks.  Outputs are emitted from the last stage's shard only (no
full-tensor psum broadcast).

Training (``pipeline_train_apply``/``make_pipeline_train``): a collective
1F1B schedule in a single ``lax.scan``.  Every tick runs one forward slot
and one backward slot on every device:

* F slot, stage ``s``, tick ``t``: microbatch ``i = t - s`` (injection
  rate one microbatch per tick, same as GPipe).
* B slot, stage ``s``, tick ``t``: microbatch ``j = t - 2(S-1) + s`` —
  the last stage backpropagates a microbatch the same tick it finishes
  its forward; the cotangent then hops backward one stage per tick.

Total ticks ``M + 2(S-1)``; bubble fraction ``2(S-1) / (M + 2(S-1))``
(each tick is one F plus one B application, so the 2(S-1) idle slots are
the textbook 1F1B bubble ``(S-1)(t_F + t_B)``).  The schedule's memory
profile is what distinguishes 1F1B from GPipe: a stage holds at most
``2(S-1-s) + 1 <= 2S-1`` in-flight activations, so the stash is a ring
buffer of depth ``stash_depth(S) = 2(S-1) + 1`` (+1 trash slot for
invalid ticks) — O(S), independent of M.  Backward slots rematerialise
the stage forward inside ``jax.vjp`` (activation-checkpoint trade).

Design constraint (standard for collective SPMD pipelines): stages are
homogeneous — every stage maps activations ``[mb, ...] -> [mb, ...]`` of
one shape/dtype.  Token embedding runs outside the pipeline (inject
embedded activations and chain its gradient through ``return_dx``); the
head is differentiated inside the last stage's loss when ``head_params``
is supplied (``with_head=True``) — models/pp_llama.py wires both for the
Llama family.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .sharding import shard_map_fn


def pipeline_ticks(n_micro: int, n_stages: int, *, train: bool = True) -> int:
    """Scan length of the schedule (see module docstring)."""
    return n_micro + (2 if train else 1) * (n_stages - 1)


def bubble_fraction(n_micro: int, n_stages: int) -> float:
    """Fraction of 1F1B tick-slots that are idle."""
    return 2 * (n_stages - 1) / pipeline_ticks(n_micro, n_stages)


def stash_depth(n_stages: int) -> int:
    """Max in-flight activations any stage holds under 1F1B: O(S), not O(M)."""
    return 2 * (n_stages - 1) + 1


def pipeline_apply(stage_fn: Callable, stage_params, microbatches, axis_name: str):
    """Per-device forward body (call inside shard_map).

    ``stage_params``: this device's stage parameters (leading pp dim already
    sharded away by shard_map).  ``microbatches``: [M, mb, ...] -- the full
    microbatch stream (replicated; only stage 0 reads it).  Returns
    [M, mb, ...] outputs (valid on the last stage; other stages return
    zeros, letting the caller gather from the last shard).
    """
    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = m + n - 1

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (while available); others take the
        # activation handed over from the previous stage.
        inject = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(stage == 0, inject, state)
        y = stage_fn(stage_params, x)
        # Hand activations down the pipe: stage i -> stage i+1.
        state_next = lax.ppermute(
            y, axis_name, [(i, (i + 1) % n) for i in range(n)]
        )
        # Last stage emits: its output for tick t corresponds to microbatch
        # t - (n - 1).
        out_idx = t - (n - 1)
        emit = (stage == n - 1) & (out_idx >= 0)
        outputs = lax.cond(
            emit,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        return (state_next, outputs), None

    init_state = jnp.zeros(mb_shape, microbatches.dtype)
    init_out = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    (_, outputs), _ = lax.scan(tick, (init_state, init_out), jnp.arange(ticks))
    return outputs


def make_pipeline(mesh, stage_fn: Callable, axis_name: str = "pp"):
    """Jitted global-view forward pipeline.

    ``stage_params`` global view: leading dim = number of stages, sharded
    over ``axis_name``; ``microbatches`` replicated in.  Outputs come from
    the LAST stage's shard only — no cross-device broadcast; the caller
    receives the [M, mb, ...] tensor and any further resharding moves just
    that one shard.
    """
    n = mesh.shape[axis_name]

    def local(stage_params, microbatches):
        out = pipeline_apply(stage_fn, stage_params, microbatches, axis_name)
        return out[None]  # [1, M, ...]: this stage's emission slot

    stacked = shard_map_fn(
        mesh, local, in_specs=(P(axis_name), P()), out_specs=P(axis_name),
    )

    def run(stage_params, microbatches):
        return stacked(stage_params, microbatches)[n - 1]

    return jax.jit(run)


def pipeline_train_apply(stage_fn: Callable, loss_fn: Callable, stage_params,
                         inputs, targets, axis_name: str, head_params=None,
                         return_dx: bool = False, with_aux: bool = False):
    """Per-device 1F1B body (call inside shard_map).

    ``inputs``: [M, mb, ...] activation microbatches (replicated; stage 0
    injects them).  ``targets``: [M, ...] per-microbatch targets consumed by
    the last stage's loss — ``loss_fn(y, target)``, or, with
    ``head_params`` given, ``loss_fn(head_params, y, target)`` so the model
    head (final norm / lm_head / ...) is differentiated too.  Returns
    ``(loss, dparams[, dhead][, dinputs])``:

    * ``dparams`` — THIS stage's parameter gradient (f32), exactly the
      sharded gradient the optimizer wants;
    * ``dhead`` (iff ``head_params``) — head gradient, psum-replicated;
    * ``dinputs`` (iff ``return_dx``) — [1, M, mb, ...] cotangent of
      ``inputs``, valid on stage 0 ONLY (zeros elsewhere): emit it with
      ``out_specs P(axis)`` and read the first shard, like
      ``pipeline_apply``'s last-stage outputs — no activation-sized
      collective.  The caller chains it into whatever produced the
      activations (embedding).

    Scalar loss aside, the head-grad psum is the only collective beyond
    the activation/cotangent hops, and it is gradient-sized, not per-tick.

    ``with_aux``: ``stage_fn`` returns ``(y, aux)`` where ``aux`` is a
    scalar loss contribution (f32, already coefficient-scaled — e.g. the
    MoE balance term of this stage's layers).  Every stage's aux joins
    the reported loss, and its gradient chains exactly like the main
    loss: the last stage adds its aux inside the loss closure, mid
    stages seed the aux output with cotangent 1 in the backward vjp —
    so ``d aux_s / d x`` rides the same backward hops and reaches every
    upstream stage's parameters.
    """
    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = inputs.shape[0]
    mb_shape = inputs.shape[1:]
    depth = stash_depth(n)
    ticks = pipeline_ticks(m, n, train=True)

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]

    def f32_zeros_like(tree):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), tree)

    def apply_stage(p, x):
        """Uniform (y, aux) view: dense stages get a constant-zero aux
        (no gradient path, so the vjp cotangent on it is free)."""
        out = stage_fn(p, x)
        return out if with_aux else (out, jnp.float32(0))

    def tick(carry, t):
        fwd_in, bwd_in, stash, dparams, dhead, dx_buf, loss_acc = carry

        # ---- F slot: microbatch i = t - stage ----
        i = t - stage
        f_valid = (i >= 0) & (i < m)
        x = jnp.where(stage == 0, inputs[jnp.clip(i, 0, m - 1)], fwd_in)
        y, aux_f = apply_stage(stage_params, x)
        # Aux VALUE accounting happens here in the F slot (the last stage
        # is excluded: its aux joins loss_j inside the backward's loss
        # closure, which would double-count it).  Aux GRADIENTS come from
        # the backward slots below.
        loss_acc = loss_acc + jnp.where(f_valid & (stage != n - 1),
                                        aux_f, 0.0)
        # Stash the stage INPUT for the backward remat; invalid ticks write
        # to the dedicated trash slot `depth`.
        slot = jnp.where(f_valid, jax.lax.rem(jnp.clip(i, 0, m - 1), depth),
                         depth)
        stash = lax.dynamic_update_index_in_dim(stash, x, slot, axis=0)
        # Scan carries have fixed dtype: stages must be dtype-preserving
        # (homogeneous-stage constraint); the cast makes that explicit.
        fwd_out = lax.ppermute(y.astype(inputs.dtype), axis_name, fwd_perm)

        # ---- B slot: microbatch j = t - 2(n-1) + stage ----
        j = t - 2 * (n - 1) + stage
        b_valid = (j >= 0) & (j < m)
        jc = jnp.clip(j, 0, m - 1)
        x_saved = stash[jax.lax.rem(jc, depth)]
        target = targets[jc]

        def last_branch(_):
            # Backprop through loss o stage in one vjp; at the last stage
            # j == i, so x_saved is the activation stashed THIS tick.
            # The stage's own aux joins the loss closure, so loss_j and
            # the grads both carry it.
            if head_params is None:
                def h(p, x):
                    yy, aa = apply_stage(p, x)
                    return loss_fn(yy, target) + aa

                loss_j, (dp, dx) = jax.value_and_grad(h, argnums=(0, 1))(
                    stage_params, x_saved)
                dh = dhead  # zeros-shaped placeholder, unused
            else:
                def h(p, x, hp):
                    yy, aa = apply_stage(p, x)
                    return loss_fn(hp, yy, target) + aa

                loss_j, (dp, dx, dh) = jax.value_and_grad(
                    h, argnums=(0, 1, 2))(stage_params, x_saved, head_params)
                dh = f32_tree(dh)
            return (f32_tree(dp), dx.astype(jnp.float32), dh,
                    jnp.asarray(loss_j, jnp.float32))

        def mid_branch(_):
            (yy, aa), vjp_fn = jax.vjp(apply_stage, stage_params, x_saved)
            # Cotangent 1 on the aux output: this stage's balance term
            # differentiates into (dp, dx) alongside the downstream loss.
            dp, dx = vjp_fn((bwd_in.astype(yy.dtype),
                             jnp.ones((), aa.dtype)))
            return (f32_tree(dp), dx.astype(jnp.float32),
                    f32_zeros_like(head_params), jnp.float32(0))

        def f32_tree(tree):
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), tree)

        dp, dx, dh, loss_j = lax.cond(stage == n - 1, last_branch, mid_branch,
                                      None)
        mask = b_valid.astype(jnp.float32)
        dparams = jax.tree_util.tree_map(
            lambda acc, g: acc + mask * g, dparams, dp)
        loss_acc = loss_acc + mask * loss_j
        if head_params is not None:
            dhead = jax.tree_util.tree_map(
                lambda acc, g: acc + mask * g, dhead, dh)
        if return_dx:
            # Stage 0's backward output is d(inputs[j]); other stages (and
            # invalid ticks) write zeros, which never clobber a real value:
            # stage 0's invalid ticks all precede its j=0 backward.
            dx_local = jnp.where(stage == 0, dx * mask, jnp.zeros_like(dx))
            dx_buf = lax.dynamic_update_index_in_dim(dx_buf, dx_local, jc,
                                                     axis=0)
        bwd_out = lax.ppermute(dx * mask, axis_name, bwd_perm)

        return (fwd_out, bwd_out, stash, dparams, dhead, dx_buf, loss_acc), None

    init = (
        jnp.zeros(mb_shape, inputs.dtype),
        jnp.zeros(mb_shape, jnp.float32),
        jnp.zeros((depth + 1,) + mb_shape, inputs.dtype),
        f32_zeros_like(stage_params),
        f32_zeros_like(head_params),
        jnp.zeros((m,) + mb_shape, jnp.float32) if return_dx
        else jnp.zeros((), jnp.float32),
        jnp.float32(0),
    )
    (_, _, _, dparams, dhead, dx_buf, loss_acc), _ = lax.scan(
        tick, init, jnp.arange(ticks))
    # Only the last stage saw losses; the scalar psum is the single
    # per-step cross-stage collective beyond the hops and optional outputs.
    loss = lax.psum(loss_acc, axis_name) / m
    # Cotangents were seeded per-microbatch with scale 1, so the stash is a
    # sum over microbatches; the returned gradient must match the MEAN loss.
    dparams = jax.tree_util.tree_map(lambda g: g / m, dparams)
    out = (loss, dparams)
    if head_params is not None:
        dhead = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name) / m, dhead)
        out += (dhead,)
    if return_dx:
        out += (dx_buf[None] / m,)  # [1, M, mb, ...]: this stage's shard
    return out


def dp_compose(mesh, dp_axis: "str | None", axis_name: str, *,
               with_head: bool, return_dx: bool,
               ep_axis: "str | None" = None, expert_spec=None):
    """Shared dp-composition plumbing for BOTH 1F1B builders (plain and
    interleaved): validates ``dp_axis``, builds the input/dx specs, and
    returns the local-output reducer.

    Returns ``(data_spec, dx_spec, dp_reduce)``: inputs/targets shard
    their dim-1 (within-microbatch batch) over dp; the local dx buffer
    ``[1, M, mb, ...]`` shards dim 2; ``dp_reduce`` pmean-averages loss /
    param grads / head grads over dp and scales dinputs by 1/ndp (the
    per-shard cotangent differentiates the dp-averaged loss — without the
    factor an embedding chained into it would be ndp x the stage grads'
    scale).

    ``ep_axis``: an EXPERT-parallel data axis — tokens shard over it like
    dp (dim 1 of inputs/targets), but stage-parameter leaves marked True
    in ``expert_spec`` (a bool pytree matching the stage params) hold
    DIFFERENT experts on each ep rank, so their gradients must not be
    averaged across ep.  The expert all-to-all's backward transpose has
    already summed every rank's cotangent contribution into the owning
    rank's expert grad, so the mean-loss scale is ``grad / ep`` (dense
    leaves: the usual pmean over both axes).
    """
    if dp_axis is not None and dp_axis not in mesh.shape:
        raise ValueError(f"dp_axis={dp_axis!r} is not an axis of {mesh.shape}")
    if dp_axis == axis_name:
        # Sharding the batch over the STAGE axis would run every schedule
        # slot on a different batch slice and a different stage at once —
        # plausible-looking garbage, not an error, on return_dx=False paths.
        raise ValueError(f"dp_axis must differ from the pipeline axis "
                         f"{axis_name!r}")
    if (ep_axis is None) != (expert_spec is None):
        # ep without the mask would pmean DIFFERENT experts' grads across
        # ep ranks (plausible-looking, wrong); the mask without ep has no
        # axis to reduce over.  Fail loudly instead.
        raise ValueError("ep_axis and expert_spec must be given together")
    if ep_axis is not None:
        if ep_axis not in mesh.shape:
            raise ValueError(
                f"ep_axis={ep_axis!r} is not an axis of {mesh.shape}")
        if ep_axis in (axis_name, dp_axis):
            raise ValueError(f"ep_axis must differ from the pipeline and dp "
                             f"axes, got {ep_axis!r}")
    axes = tuple(a for a in (dp_axis, ep_axis) if a is not None)
    data_spec = P(None, axes) if axes else P()
    dx_spec = P(axis_name, None, axes) if axes else P(axis_name)

    def grad_reduce(g, is_expert):
        if is_expert:
            g = g / lax.axis_size(ep_axis)
            return lax.pmean(g, dp_axis) if dp_axis is not None else g
        return lax.pmean(g, axes)

    def dp_reduce(out):
        if not axes:
            return out
        loss = lax.pmean(out[0], axes)
        if expert_spec is None:
            dparams = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, axes), out[1])
        else:
            dparams = jax.tree_util.tree_map(grad_reduce, out[1],
                                             expert_spec)
        rest = out[2:]
        if with_head:
            dhead = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, axes), rest[0])
            rest = (dhead,) + rest[1:]
        if return_dx:
            scale = 1
            for a in axes:
                scale = scale * lax.axis_size(a)
            rest = rest[:-1] + (rest[-1] / scale,)
        return (loss, dparams) + rest

    return data_spec, dx_spec, dp_reduce


def make_pipeline_train(mesh, stage_fn: Callable, loss_fn: Callable,
                        axis_name: str = "pp", *, with_head: bool = False,
                        return_dx: bool = False, dp_axis: str | None = None,
                        with_aux: bool = False, ep_axis: str | None = None,
                        param_specs=None, expert_spec=None):
    """Jitted global-view 1F1B training step builder.

    Returns ``grad_step(stage_params, inputs, targets) -> (loss, grads)``
    with ``stage_params``/``grads`` global ``[S, ...]`` sharded over
    ``axis_name`` and ``inputs [M, mb, ...]``/``targets [M, ...]``
    replicated.  Feed ``grads`` straight to an optax update — they are
    already laid out like the params.

    ``with_head``: the step takes an extra ``head_params`` pytree consumed
    by ``loss_fn(head_params, y, target)`` and additionally returns its
    (replicated) gradient.  ``return_dx``: additionally return the
    [M, mb, ...] cotangent of ``inputs`` — chain it into the embedding (or
    whatever produced the activations); it is emitted from stage 0's shard
    only (sharded out_spec + index, no activation-sized collective).
    Extras are appended to the result in that order.

    ``dp_axis``: compose the pipeline with data parallelism on a pp x dp
    mesh — each dp group runs an independent 1F1B schedule over its slice
    of every microbatch.  Dim 1 of inputs/targets (``mb``, the
    within-microbatch batch size — NOT the microbatch count ``M``, which
    stays whole on every group) shards over ``dp_axis`` and must divide
    by it; loss / parameter grads / head grads are pmean'd over dp (one
    gradient-sized collective per step, the standard DP all-reduce).  The
    returned ``dinputs`` cotangent stays per-shard — it differentiates
    THIS shard's inputs against the dp-averaged loss (the 1/ndp factor is
    applied), so chaining it into an embedding yields grads on the same
    scale as ``dparams``.

    ``with_aux``: ``stage_fn`` returns ``(y, aux)`` and every stage's aux
    scalar joins the loss and the gradients (see
    :func:`pipeline_train_apply`).  ``ep_axis`` + ``expert_spec``: tokens
    additionally shard over an expert-parallel axis whose expert-table
    gradient leaves get expert-aware reduction (see :func:`dp_compose`).
    ``param_specs``: a PartitionSpec pytree matching ``stage_params`` for
    when leaves shard beyond the leading stage dim (expert tables over
    ep); defaults to ``P(axis_name)`` on every leaf.  Gradients come back
    sharded exactly like the params.
    """
    data_spec, dx_spec, dp_reduce = dp_compose(
        mesh, dp_axis, axis_name, with_head=with_head, return_dx=return_dx,
        ep_axis=ep_axis, expert_spec=expert_spec)
    p_spec = P(axis_name) if param_specs is None else param_specs

    if with_head:
        def local(stage_params, head_params, inputs, targets):
            return dp_reduce(pipeline_train_apply(
                stage_fn, loss_fn, stage_params, inputs, targets, axis_name,
                head_params=head_params, return_dx=return_dx,
                with_aux=with_aux))

        in_specs = (p_spec, P(), data_spec, data_spec)
        out_specs = (P(), p_spec, P()) + ((dx_spec,) if return_dx else ())
    else:
        def local(stage_params, inputs, targets):
            return dp_reduce(pipeline_train_apply(
                stage_fn, loss_fn, stage_params, inputs, targets, axis_name,
                return_dx=return_dx, with_aux=with_aux))

        in_specs = (p_spec, data_spec, data_spec)
        out_specs = (P(), p_spec) + ((dx_spec,) if return_dx else ())

    staged = shard_map_fn(mesh, local, in_specs=in_specs, out_specs=out_specs)
    if not return_dx:
        return jax.jit(staged)

    def run(*args):
        out = staged(*args)
        return out[:-1] + (out[-1][0],)  # dinputs lives on stage 0's shard

    return jax.jit(run)
