"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context substrate (the capability SURVEY.md section 5 calls out as the
point of the tagged-P2P primitives: "ring attention = asend/arecv to ring
neighbors + overlap, i.e. CollectivePermute").  Implemented TPU-native: each
device owns a sequence shard of q/k/v; kv shards rotate around the mesh axis
with ``lax.ppermute`` while every device accumulates online-softmax partials
against its resident queries.  XLA overlaps the ppermute DMA with the next
block's matmuls, so the ring rides ICI concurrently with MXU compute.

Exactness comes from the associative merge in ops/attention.py -- blocks may
arrive in any rotation order, which is also what makes the accumulation
robust to mesh axis ordering.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    finalize_partial,
    merge_partials,
    partial_attention,
    repeat_kv,
    zero_partial,
)
from ..ops.collectives import ring_shift
from .sharding import shard_map_fn


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True,
                   sm_scale: Optional[float] = None):
    """Per-device body (call inside shard_map): q/k/v are local sequence
    shards ``[B, H, T_local, D]``; returns the local output shard.

    Grouped-query kv is accepted unexpanded (``k/v`` with fewer heads): the
    ring rotates the *narrow* kv shards and expands per step, so ICI moves
    1/n_rep of the naive traffic.  Rotation schedule: after step ``i`` the
    device holds kv shard ``(my_index - i) mod n``; global offsets feed the
    causal mask so no cross-shard attention is wrongly masked or admitted.
    The last compute step skips the rotation (n-1 ppermutes for n shards).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = my * t_local
    n_rep = q.shape[1] // k.shape[1]

    def compute(i, acc, k_cur, v_cur):
        src = (my - i) % n  # owner of the kv shard currently resident here
        part = partial_attention(
            q, repeat_kv(k_cur, n_rep), repeat_kv(v_cur, n_rep),
            q_offset=q_off, kv_offset=src * t_local,
            causal=causal, sm_scale=sm_scale,
        )
        return merge_partials(acc, part)

    def body(i, carry):
        acc, k_cur, v_cur = carry
        acc = compute(i, acc, k_cur, v_cur)
        # Rotate kv to the next device; XLA overlaps this ppermute with the
        # next iteration's compute.
        k_cur = ring_shift(k_cur, axis_name, 1)
        v_cur = ring_shift(v_cur, axis_name, 1)
        return acc, k_cur, v_cur

    acc, k_last, v_last = lax.fori_loop(0, n - 1, body, (zero_partial(q), k, v))
    acc = compute(n - 1, acc, k_last, v_last)
    return finalize_partial(*acc, out_dtype=q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp", *, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """Jitted global-view ring attention: q/k/v are global arrays sharded on
    the sequence dimension over ``axis_name`` ([B, H, S, D], S sharded)."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return ring_attention(q, k, v, axis_name, causal=causal, sm_scale=sm_scale)

    return jax.jit(shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec))
