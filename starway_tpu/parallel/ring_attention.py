"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context substrate (the capability SURVEY.md section 5 calls out as the
point of the tagged-P2P primitives: "ring attention = asend/arecv to ring
neighbors + overlap, i.e. CollectivePermute").  Implemented TPU-native: each
device owns a sequence shard of q/k/v; kv shards rotate around the mesh axis
with ``lax.ppermute`` while every device accumulates online-softmax partials
against its resident queries.  XLA overlaps the ppermute DMA with the next
block's matmuls, so the ring rides ICI concurrently with MXU compute.

Exactness comes from the associative merge in ops/attention.py -- blocks may
arrive in any rotation order, which is also what makes the accumulation
robust to mesh axis ordering.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    finalize_partial,
    merge_partials,
    partial_attention,
    repeat_kv,
    zero_partial,
)
from ..ops.collectives import ring_shift
from .sharding import shard_map_fn


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True,
                   sm_scale: Optional[float] = None):
    """Per-device body (call inside shard_map): q/k/v are local sequence
    shards ``[B, H, T_local, D]``; returns the local output shard.

    Grouped-query kv is accepted unexpanded (``k/v`` with fewer heads): the
    ring rotates the *narrow* kv shards and expands per step, so ICI moves
    1/n_rep of the naive traffic.  Rotation schedule: after step ``i`` the
    device holds kv shard ``(my_index - i) mod n``; global offsets feed the
    causal mask so no cross-shard attention is wrongly masked or admitted.
    The last compute step skips the rotation (n-1 ppermutes for n shards).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = my * t_local
    n_rep = q.shape[1] // k.shape[1]

    def compute(i, acc, k_cur, v_cur):
        src = (my - i) % n  # owner of the kv shard currently resident here
        part = partial_attention(
            q, repeat_kv(k_cur, n_rep), repeat_kv(v_cur, n_rep),
            q_offset=q_off, kv_offset=src * t_local,
            causal=causal, sm_scale=sm_scale,
        )
        return merge_partials(acc, part)

    def body(i, carry):
        acc, k_cur, v_cur = carry
        acc = compute(i, acc, k_cur, v_cur)
        # Rotate kv to the next device; XLA overlaps this ppermute with the
        # next iteration's compute.
        k_cur = ring_shift(k_cur, axis_name, 1)
        v_cur = ring_shift(v_cur, axis_name, 1)
        return acc, k_cur, v_cur

    acc, k_last, v_last = lax.fori_loop(0, n - 1, body, (zero_partial(q), k, v))
    acc = compute(n - 1, acc, k_last, v_last)
    return finalize_partial(*acc, out_dtype=q.dtype)


def zigzag_indices(s: int, n: int) -> np.ndarray:
    """Global sequence permutation for the zigzag causal layout.

    The plain ring layout is causally imbalanced: device ``d`` has useful
    (unmasked) work on only ``d+1`` of ``n`` ring steps, and SPMD lockstep
    makes every step as slow as the busiest device -- so half the ring's
    MXU time is spent computing fully-masked scores.  Zigzag (the "striped"
    fix, cf. Brandon et al., Striped Attention, arXiv:2311.09431) gives
    each device one block from the front of the sequence and its mirror
    from the back: blocks ``d`` and ``2n-1-d``.  Every device then has
    exactly one fully-live pair plus one conditionally-live pair per step
    -- uniform work, ~2x causal wall-clock at scale.

    Returns the gather indices (length ``s``, requires ``2n | s``) mapping
    the natural sequence into zigzag order; invert with ``np.argsort``.
    """
    if s % (2 * n):
        raise ValueError(f"zigzag needs sequence length divisible by 2n={2*n}, got {s}")
    sb = s // (2 * n)
    blocks = []
    for d in range(n):
        blocks.append(d)
        blocks.append(2 * n - 1 - d)
    return np.concatenate([np.arange(b * sb, (b + 1) * sb) for b in blocks])


def zigzag_ring_attention(q, k, v, axis_name: str, *, sm_scale: Optional[float] = None):
    """Per-device body (call inside shard_map) for causal zigzag ring
    attention.  Local shards are in zigzag layout (see :func:`zigzag_indices`):
    the first half of the local sequence is original block ``my`` (global
    offset ``my*sb``), the second half is block ``2n-1-my``.

    Per ring step the four (q-half, kv-half) pairs are either fully live,
    diagonal, or fully in the future; the future pairs are skipped with
    ``lax.cond`` so no MXU time is spent on all-masked scores:

    * ``q_hi  vs kv_lo`` -- always live (back blocks see all front blocks)
    * ``q_lo  vs kv_lo`` -- live iff ``my >= src`` (diagonal at ``my == src``)
    * ``q_hi  vs kv_hi`` -- live iff ``my <= src``
    * ``q_lo  vs kv_hi`` -- never live (front blocks never see back blocks)

    Exactness comes from the same associative merge as :func:`ring_attention`;
    skipped pairs contribute nothing by construction.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    if q.shape[2] % 2:
        raise ValueError(
            f"zigzag local sequence must be even (two half-blocks), got {q.shape[2]}"
        )
    sb = q.shape[2] // 2
    n_rep = q.shape[1] // k.shape[1]

    q_lo, q_hi = q[:, :, :sb], q[:, :, sb:]
    off_lo = my * sb                 # global offset of our front block
    off_hi = (2 * n - 1 - my) * sb   # global offset of our mirrored back block

    def compute(src, acc_lo, acc_hi, k_cur, v_cur):
        ke = repeat_kv(k_cur, n_rep)
        ve = repeat_kv(v_cur, n_rep)
        k_lo, k_hi = ke[:, :, :sb], ke[:, :, sb:]
        v_lo, v_hi = ve[:, :, :sb], ve[:, :, sb:]
        src_lo = src * sb
        src_hi = (2 * n - 1 - src) * sb

        # Back blocks start at >= n*sb while front blocks end at <= n*sb:
        # this pair's causal mask is provably all-ones, so skip the mask.
        acc_hi = merge_partials(
            acc_hi,
            partial_attention(q_hi, k_lo, v_lo, q_offset=off_hi,
                              kv_offset=src_lo, causal=False, sm_scale=sm_scale),
        )
        acc_lo = lax.cond(
            my >= src,
            lambda a: merge_partials(
                a,
                partial_attention(q_lo, k_lo, v_lo, q_offset=off_lo,
                                  kv_offset=src_lo, causal=True, sm_scale=sm_scale),
            ),
            lambda a: a,
            acc_lo,
        )
        acc_hi = lax.cond(
            my <= src,
            lambda a: merge_partials(
                a,
                partial_attention(q_hi, k_hi, v_hi, q_offset=off_hi,
                                  kv_offset=src_hi, causal=True, sm_scale=sm_scale),
            ),
            lambda a: a,
            acc_hi,
        )
        return acc_lo, acc_hi

    def body(i, carry):
        acc_lo, acc_hi, k_cur, v_cur = carry
        src = (my - i) % n
        acc_lo, acc_hi = compute(src, acc_lo, acc_hi, k_cur, v_cur)
        k_cur = ring_shift(k_cur, axis_name, 1)
        v_cur = ring_shift(v_cur, axis_name, 1)
        return acc_lo, acc_hi, k_cur, v_cur

    acc_lo, acc_hi, k_last, v_last = lax.fori_loop(
        0, n - 1, body, (zero_partial(q_lo), zero_partial(q_hi), k, v)
    )
    acc_lo, acc_hi = compute((my - (n - 1)) % n, acc_lo, acc_hi, k_last, v_last)
    out_lo = finalize_partial(*acc_lo, out_dtype=q.dtype)
    out_hi = finalize_partial(*acc_hi, out_dtype=q.dtype)
    return jnp.concatenate([out_lo, out_hi], axis=2)


def zigzag_wrap(inner, n: int):
    """Wrap a zigzag-layout attention callable (global view, natural-order
    in/out): permutes q/k/v into zigzag order, runs ``inner``, inverts the
    permutation on the output.  Persistent-layout users skip this and call
    :func:`zigzag_ring_attention` directly inside their own shard_map,
    keeping activations zigzagged across layers and paying the shuffle
    once."""

    def fn(q, k, v):
        perm = zigzag_indices(q.shape[2], n)
        inv = np.argsort(perm)
        qz = jnp.take(q, perm, axis=2)
        kz = jnp.take(k, perm, axis=2)
        vz = jnp.take(v, perm, axis=2)
        return jnp.take(inner(qz, kz, vz), inv, axis=2)

    return fn


def make_zigzag_ring_attention(mesh, axis_name: str = "sp", *,
                               sm_scale: Optional[float] = None):
    """Jitted global-view causal ring attention in the load-balanced zigzag
    layout: q/k/v are natural-order global arrays ``[B, H, S, D]`` sharded
    on the sequence dimension; the permutation into and out of zigzag order
    is applied at the jit boundary."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return zigzag_ring_attention(q, k, v, axis_name, sm_scale=sm_scale)

    inner = shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(zigzag_wrap(inner, mesh.shape[axis_name]))


def make_ring_attention(mesh, axis_name: str = "sp", *, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """Jitted global-view ring attention: q/k/v are global arrays sharded on
    the sequence dimension over ``axis_name`` ([B, H, S, D], S sharded)."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return ring_attention(q, k, v, axis_name, causal=causal, sm_scale=sm_scale)

    return jax.jit(shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec))
