"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context substrate (the capability SURVEY.md section 5 calls out as the
point of the tagged-P2P primitives: "ring attention = asend/arecv to ring
neighbors + overlap, i.e. CollectivePermute").  Implemented TPU-native: each
device owns a sequence shard of q/k/v; kv shards rotate around the mesh axis
with ``lax.ppermute`` while every device accumulates online-softmax partials
against its resident queries.  XLA overlaps the ppermute DMA with the next
block's matmuls, so the ring rides ICI concurrently with MXU compute.

Exactness comes from the associative merge in ops/attention.py -- blocks may
arrive in any rotation order, which is also what makes the accumulation
robust to mesh axis ordering.

Both ring variants carry a ``jax.custom_vjp``:

* forward: per-step partials come from the Pallas kernel
  (ops/pallas_attention.py::flash_partial, ~7x the lax step rate on TPU) or
  from the lax path elsewhere, selected per-backend at trace time.
* backward: a second ring pass.  Each device keeps its q/do/lse/delta
  resident and accumulates dq locally, while dk/dv accumulators *rotate
  with their kv shard* -- after the full rotation each shard's gradient
  arrives back at its home device having summed every device's
  contribution.  Per-step math uses the globally merged lse/delta, so each
  step's contribution is exactly its slice of the full attention gradient
  (ops/pallas_attention.py::flash_partial_bwd).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import (
    NEG_BIG,
    finalize_partial,
    merge_partials,
    partial_attention,
    repeat_kv,
    zero_partial,
)
from ..ops.collectives import ring_shift
from .sharding import shard_map_fn


def _use_kernel_default() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# per-step primitives (kernel + lax pairs, same contract)
# ---------------------------------------------------------------------------


def _step_fwd(q, k, v, q_off, kv_off, causal, sm_scale, use_kernel,
              window=None):
    """One kv shard's unnormalised partial: (o f32, m f32, l f32).

    ``window``: sliding-window band (requires causal) — routed through the
    lax path (the flash_partial kernel carries no band support; windowed
    rings skip most pairs outright anyway, see _ring_fwd_impl)."""
    if use_kernel and window is None:
        from ..ops.pallas_attention import flash_partial

        return flash_partial(q, k, v, q_off, kv_off, causal=causal,
                             sm_scale=sm_scale)
    n_rep = q.shape[1] // k.shape[1]
    return partial_attention(
        q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
        q_offset=q_off, kv_offset=kv_off, causal=causal, sm_scale=sm_scale,
        window=window,
    )


def _step_bwd(q, do, k, v, lse, delta, q_off, kv_off, causal, sm_scale,
              use_kernel, window=None):
    """One kv shard's gradient contributions: (dq, dk, dv), f32, dk/dv
    grouped.  lse/delta are the globally merged statistics."""
    if use_kernel and window is None:
        from ..ops.pallas_attention import flash_partial_bwd

        return flash_partial_bwd(q, do, k, v, lse, delta, q_off, kv_off,
                                 causal=causal, sm_scale=sm_scale)
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    n_rep = hq // hkv
    ke = repeat_kv(k, n_rep).astype(jnp.float32)
    ve = repeat_kv(v, n_rep).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, ke) * sm_scale
    if causal:
        q_pos = q_off + jnp.arange(tq)
        kv_pos = kv_off + jnp.arange(tk)
        keep = q_pos[:, None] >= kv_pos[None, :]
        if window is not None:
            keep = keep & (kv_pos[None, :] > q_pos[:, None] - window)
        s = jnp.where(keep[None, None], s, NEG_BIG)
    p = jnp.exp(s - lse[..., None])  # normalised; masked entries -> 0
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, ve)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, ke) * sm_scale
    dke = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * sm_scale
    dve = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dk = dke.reshape(b, hkv, n_rep, tk, d).sum(2)
    dv = dve.reshape(b, hkv, n_rep, tk, d).sum(2)
    return dq, dk, dv


def _lse_of(acc):
    """Merged partial -> log-sum-exp (f32), the backward's row statistic."""
    o, m, l = acc
    return m + jnp.log(jnp.maximum(l, 1e-30))


def _rotate(xs, axis_name):
    return tuple(ring_shift(x, axis_name, 1) for x in xs)


# ---------------------------------------------------------------------------
# plain ring (natural layout)
# ---------------------------------------------------------------------------


def _band_live(q_off, kv_off, tq, tk, causal, window):
    """Does the (q block, kv block) pair contribute anything under the
    causal/window band?  False -> the whole tile is masked and its ring
    step can skip compute outright (the windowed-ring win: at
    window << S only ~window/t_local + 1 of the n steps are live)."""
    live = jnp.asarray(True)
    if causal:
        live = q_off + tq - 1 >= kv_off          # some key is in the past
    if window is not None:
        live = live & (q_off - (kv_off + tk - 1) < window)  # ...and close
    return live


def _ring_steps(n: int, t_local: int, window) -> int:
    """How many ring steps can EVER be live under the band: device my
    attends shard my - i only while i * t_local reaches back < window
    (plus its own diagonal).  Static — window and shard sizes are
    trace-time constants — so both loops AND rotations stop after the
    band: communication scales with the window, not the sequence."""
    if window is None:
        return n
    return min(n, (window - 2 + t_local) // t_local + 1)


def _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, use_kernel,
                   window=None):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = my * t_local
    steps = _ring_steps(n, t_local, window)

    def compute(i, acc, k_cur, v_cur):
        src = (my - i) % n  # owner of the kv shard currently resident here
        kv_off = src * t_local

        def live_part(_):
            return _step_fwd(q, k_cur, v_cur, q_off, kv_off, causal,
                             sm_scale, use_kernel, window)

        if window is None:
            part = live_part(None)
        else:
            # merge with the identity partial (m=-inf, l=0) when skipped.
            part = lax.cond(
                _band_live(q_off, kv_off, t_local, t_local, causal, window),
                live_part, lambda _: zero_partial(q), None)
        return merge_partials(acc, part)

    def body(i, carry):
        acc, k_cur, v_cur = carry
        acc = compute(i, acc, k_cur, v_cur)
        # Rotate kv to the next device; XLA overlaps this ppermute with the
        # next iteration's compute.
        k_cur, v_cur = _rotate((k_cur, v_cur), axis_name)
        return acc, k_cur, v_cur

    acc, k_last, v_last = lax.fori_loop(0, steps - 1, body,
                                        (zero_partial(q), k, v))
    acc = compute(steps - 1, acc, k_last, v_last)
    out = finalize_partial(*acc, out_dtype=q.dtype)
    return out, _lse_of(acc)


def _ring_bwd_impl(q, k, v, out, lse, do, axis_name, causal, sm_scale,
                   use_kernel, window=None):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = my * t_local
    steps = _ring_steps(n, t_local, window)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    def step(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - i) % n
        kv_off = src * t_local

        def live_grads(_):
            return _step_bwd(q, do, k_cur, v_cur, lse, delta, q_off,
                             kv_off, causal, sm_scale, use_kernel, window)

        if window is None:
            dq_c, dk_c, dv_c = live_grads(None)
        else:
            dq_c, dk_c, dv_c = lax.cond(
                _band_live(q_off, kv_off, t_local, t_local, causal, window),
                live_grads,
                lambda _: (jnp.zeros(q.shape, jnp.float32),
                           jnp.zeros(k.shape, jnp.float32),
                           jnp.zeros(v.shape, jnp.float32)), None)
        return dq + dq_c, k_cur, v_cur, dk_cur + dk_c, dv_cur + dv_c

    def body(i, carry):
        carry = step(i, carry)
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        # dk/dv accumulators rotate WITH their kv shard, so each shard's
        # gradient keeps collecting contributions device by device.
        k_cur, v_cur, dk_cur, dv_cur = _rotate(
            (k_cur, v_cur, dk_cur, dv_cur), axis_name)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    init = (jnp.zeros(q.shape, jnp.float32), k, v,
            jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32))
    carry = lax.fori_loop(0, steps - 1, body, init)
    dq, _, _, dk, dv = step(steps - 1, carry)
    # Send each kv shard's gradient home: after steps-1 in-loop rotations
    # a shard's grad sits steps-1 hops from its owner, so one ppermute of
    # the REMAINING distance closes the ring (shift 1 in the full-ring
    # case; identity skipped when the band never moved the shards).
    home = (n - (steps - 1)) % n
    if home:
        dk = ring_shift(dk, axis_name, home)
        dv = ring_shift(dv, axis_name, home)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring(q, k, v, axis_name, causal, sm_scale, use_kernel, window):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, use_kernel,
                            window)
    return out


def _ring_vjp_fwd(q, k, v, axis_name, causal, sm_scale, use_kernel, window):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                              use_kernel, window)
    return out, (q, k, v, out, lse)


def _ring_vjp_bwd(axis_name, causal, sm_scale, use_kernel, window, res, do):
    q, k, v, out, lse = res
    return _ring_bwd_impl(q, k, v, out, lse, do, axis_name, causal, sm_scale,
                          use_kernel, window)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   use_kernel: Optional[bool] = None,
                   window: Optional[int] = None):
    """Per-device body (call inside shard_map): q/k/v are local sequence
    shards ``[B, H, T_local, D]``; returns the local output shard.

    Grouped-query kv is accepted unexpanded (``k/v`` with fewer heads): the
    ring rotates the *narrow* kv shards and the per-step compute expands (or
    the Pallas kernel indexes) per head group, so ICI moves 1/n_rep of the
    naive traffic.  Rotation schedule: after step ``i`` the device holds kv
    shard ``(my_index - i) mod n``; global offsets feed the causal mask so
    no cross-shard attention is wrongly masked or admitted.  The last
    compute step skips the rotation (n-1 ppermutes for n shards).

    Differentiable: gradients run the backward ring (module docstring).

    ``window`` (requires ``causal``): Mistral-style sliding-window band.
    Ring steps whose (q shard, kv shard) pair lies wholly outside the
    band cond-skip their compute — at ``window << S`` only about
    ``window / t_local + 1`` of the ``n`` steps are live, so wall-clock
    scales with the band, not the sequence (the banded analogue of the
    zigzag causal win).  In-band steps run the lax masked path (the
    flash_partial kernel carries no band; the skipped steps dominate the
    savings).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if use_kernel is None:
        use_kernel = _use_kernel_default()
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    return _ring(q, k, v, axis_name, bool(causal), float(sm_scale),
                 bool(use_kernel), None if window is None else int(window))


# ---------------------------------------------------------------------------
# zigzag (load-balanced causal) ring
# ---------------------------------------------------------------------------


def zigzag_indices(s: int, n: int) -> np.ndarray:
    """Global sequence permutation for the zigzag causal layout.

    The plain ring layout is causally imbalanced: device ``d`` has useful
    (unmasked) work on only ``d+1`` of ``n`` ring steps, and SPMD lockstep
    makes every step as slow as the busiest device -- so half the ring's
    MXU time is spent computing fully-masked scores.  Zigzag (the "striped"
    fix, cf. Brandon et al., Striped Attention, arXiv:2311.09431) gives
    each device one block from the front of the sequence and its mirror
    from the back: blocks ``d`` and ``2n-1-d``.  Every device then has
    exactly one fully-live pair plus one conditionally-live pair per step
    -- uniform work, ~2x causal wall-clock at scale.

    Returns the gather indices (length ``s``, requires ``2n | s``) mapping
    the natural sequence into zigzag order; invert with ``np.argsort``.
    """
    if s % (2 * n):
        raise ValueError(f"zigzag needs sequence length divisible by 2n={2*n}, got {s}")
    sb = s // (2 * n)
    blocks = []
    for d in range(n):
        blocks.append(d)
        blocks.append(2 * n - 1 - d)
    return np.concatenate([np.arange(b * sb, (b + 1) * sb) for b in blocks])


def _zz_offsets(my, src, n, sb):
    """Global offsets of the four half-blocks in play at one zigzag step."""
    return dict(
        off_lo=my * sb,                   # our front block
        off_hi=(2 * n - 1 - my) * sb,     # our mirrored back block
        src_lo=src * sb,                  # visiting front block
        src_hi=(2 * n - 1 - src) * sb,    # visiting back block
    )


def _zz_fwd_impl(q, k, v, axis_name, sm_scale, use_kernel):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sb = q.shape[2] // 2

    q_lo, q_hi = q[:, :, :sb], q[:, :, sb:]

    def compute(src, acc_lo, acc_hi, k_cur, v_cur):
        o = _zz_offsets(my, src, n, sb)
        k_lo, k_hi = k_cur[:, :, :sb], k_cur[:, :, sb:]
        v_lo, v_hi = v_cur[:, :, :sb], v_cur[:, :, sb:]

        # Back blocks start at >= n*sb while front blocks end at <= n*sb:
        # this pair's causal mask is provably all-ones, so skip the mask.
        acc_hi = merge_partials(
            acc_hi,
            _step_fwd(q_hi, k_lo, v_lo, o["off_hi"], o["src_lo"], False,
                      sm_scale, use_kernel),
        )
        acc_lo = lax.cond(
            my >= src,
            lambda a: merge_partials(
                a, _step_fwd(q_lo, k_lo, v_lo, o["off_lo"], o["src_lo"],
                             True, sm_scale, use_kernel)),
            lambda a: a,
            acc_lo,
        )
        acc_hi = lax.cond(
            my <= src,
            lambda a: merge_partials(
                a, _step_fwd(q_hi, k_hi, v_hi, o["off_hi"], o["src_hi"],
                             True, sm_scale, use_kernel)),
            lambda a: a,
            acc_hi,
        )
        return acc_lo, acc_hi

    def body(i, carry):
        acc_lo, acc_hi, k_cur, v_cur = carry
        acc_lo, acc_hi = compute((my - i) % n, acc_lo, acc_hi, k_cur, v_cur)
        k_cur, v_cur = _rotate((k_cur, v_cur), axis_name)
        return acc_lo, acc_hi, k_cur, v_cur

    acc_lo, acc_hi, k_last, v_last = lax.fori_loop(
        0, n - 1, body, (zero_partial(q_lo), zero_partial(q_hi), k, v)
    )
    acc_lo, acc_hi = compute((my - (n - 1)) % n, acc_lo, acc_hi, k_last,
                             v_last)
    out = jnp.concatenate(
        [finalize_partial(*acc_lo, out_dtype=q.dtype),
         finalize_partial(*acc_hi, out_dtype=q.dtype)], axis=2)
    lse = jnp.concatenate([_lse_of(acc_lo), _lse_of(acc_hi)], axis=2)
    return out, lse


def _zz_bwd_impl(q, k, v, out, lse, do, axis_name, sm_scale, use_kernel):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sb = q.shape[2] // 2
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    q_lo, q_hi = q[:, :, :sb], q[:, :, sb:]
    do_lo, do_hi = do[:, :, :sb], do[:, :, sb:]
    lse_lo, lse_hi = lse[:, :, :sb], lse[:, :, sb:]
    d_lo, d_hi = delta[:, :, :sb], delta[:, :, sb:]

    kv_zero = jnp.zeros(k.shape[:2] + (sb,) + k.shape[3:], jnp.float32)

    def step(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - i) % n
        o = _zz_offsets(my, src, n, sb)
        k_lo, k_hi = k_cur[:, :, :sb], k_cur[:, :, sb:]
        v_lo, v_hi = v_cur[:, :, :sb], v_cur[:, :, sb:]

        # Pair hi-lo: always live, mask-free.
        dqh, dkl, dvl = _step_bwd(q_hi, do_hi, k_lo, v_lo, lse_hi, d_hi,
                                  o["off_hi"], o["src_lo"], False, sm_scale,
                                  use_kernel)
        # Pair lo-lo: live iff my >= src (diagonal at equality).
        z3 = (jnp.zeros(q_lo.shape, jnp.float32), kv_zero, kv_zero)
        dql, dkl2, dvl2 = lax.cond(
            my >= src,
            lambda: _step_bwd(q_lo, do_lo, k_lo, v_lo, lse_lo, d_lo,
                              o["off_lo"], o["src_lo"], True, sm_scale,
                              use_kernel),
            lambda: z3,
        )
        # Pair hi-hi: live iff my <= src.
        dqh2, dkh, dvh = lax.cond(
            my <= src,
            lambda: _step_bwd(q_hi, do_hi, k_hi, v_hi, lse_hi, d_hi,
                              o["off_hi"], o["src_hi"], True, sm_scale,
                              use_kernel),
            lambda: z3,
        )
        dq = dq + jnp.concatenate([dql, dqh + dqh2], axis=2)
        dk_cur = dk_cur + jnp.concatenate([dkl + dkl2, dkh], axis=2)
        dv_cur = dv_cur + jnp.concatenate([dvl + dvl2, dvh], axis=2)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    def body(i, carry):
        carry = step(i, carry)
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        k_cur, v_cur, dk_cur, dv_cur = _rotate(
            (k_cur, v_cur, dk_cur, dv_cur), axis_name)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    init = (jnp.zeros(q.shape, jnp.float32), k, v,
            jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32))
    carry = lax.fori_loop(0, n - 1, body, init)
    dq, _, _, dk, dv = step(n - 1, carry)
    dk, dv = _rotate((dk, dv), axis_name)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _zigzag(q, k, v, axis_name, sm_scale, use_kernel):
    out, _ = _zz_fwd_impl(q, k, v, axis_name, sm_scale, use_kernel)
    return out


def _zz_vjp_fwd(q, k, v, axis_name, sm_scale, use_kernel):
    out, lse = _zz_fwd_impl(q, k, v, axis_name, sm_scale, use_kernel)
    return out, (q, k, v, out, lse)


def _zz_vjp_bwd(axis_name, sm_scale, use_kernel, res, do):
    q, k, v, out, lse = res
    return _zz_bwd_impl(q, k, v, out, lse, do, axis_name, sm_scale,
                        use_kernel)


_zigzag.defvjp(_zz_vjp_fwd, _zz_vjp_bwd)


def zigzag_ring_attention(q, k, v, axis_name: str, *,
                          sm_scale: Optional[float] = None,
                          use_kernel: Optional[bool] = None):
    """Per-device body (call inside shard_map) for causal zigzag ring
    attention.  Local shards are in zigzag layout (see :func:`zigzag_indices`):
    the first half of the local sequence is original block ``my`` (global
    offset ``my*sb``), the second half is block ``2n-1-my``.

    Per ring step the four (q-half, kv-half) pairs are either fully live,
    diagonal, or fully in the future; the future pairs are skipped with
    ``lax.cond`` so no MXU time is spent on all-masked scores:

    * ``q_hi  vs kv_lo`` -- always live (back blocks see all front blocks)
    * ``q_lo  vs kv_lo`` -- live iff ``my >= src`` (diagonal at ``my == src``)
    * ``q_hi  vs kv_hi`` -- live iff ``my <= src``
    * ``q_lo  vs kv_hi`` -- never live (front blocks never see back blocks)

    Exactness comes from the same associative merge as :func:`ring_attention`;
    skipped pairs contribute nothing by construction.  Differentiable via
    the backward ring (module docstring); the backward mirrors the same
    pair liveness so skipped pairs cost nothing there either.
    """
    if q.shape[2] % 2:
        raise ValueError(
            f"zigzag local sequence must be even (two half-blocks), got {q.shape[2]}"
        )
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if use_kernel is None:
        use_kernel = _use_kernel_default()
    return _zigzag(q, k, v, axis_name, float(sm_scale), bool(use_kernel))


def zigzag_wrap(inner, n: int):
    """Wrap a zigzag-layout attention callable (global view, natural-order
    in/out): permutes q/k/v into zigzag order, runs ``inner``, inverts the
    permutation on the output.  Persistent-layout users skip this and call
    :func:`zigzag_ring_attention` directly inside their own shard_map,
    keeping activations zigzagged across layers and paying the shuffle
    once."""

    def fn(q, k, v):
        perm = zigzag_indices(q.shape[2], n)
        inv = np.argsort(perm)
        qz = jnp.take(q, perm, axis=2)
        kz = jnp.take(k, perm, axis=2)
        vz = jnp.take(v, perm, axis=2)
        return jnp.take(inner(qz, kz, vz), inv, axis=2)

    return fn


def make_zigzag_ring_attention(mesh, axis_name: str = "sp", *,
                               sm_scale: Optional[float] = None,
                               use_kernel: Optional[bool] = None):
    """Jitted global-view causal ring attention in the load-balanced zigzag
    layout: q/k/v are natural-order global arrays ``[B, H, S, D]`` sharded
    on the sequence dimension; the permutation into and out of zigzag order
    is applied at the jit boundary."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return zigzag_ring_attention(q, k, v, axis_name, sm_scale=sm_scale,
                                     use_kernel=use_kernel)

    inner = shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(zigzag_wrap(inner, mesh.shape[axis_name]))


def make_ring_attention(mesh, axis_name: str = "sp", *, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        use_kernel: Optional[bool] = None,
                        window: Optional[int] = None):
    """Jitted global-view ring attention: q/k/v are global arrays sharded on
    the sequence dimension over ``axis_name`` ([B, H, S, D], S sharded).
    ``window``: sliding-window band (see :func:`ring_attention`)."""
    spec = P(None, None, axis_name, None)

    def local(q, k, v):
        return ring_attention(q, k, v, axis_name, causal=causal,
                              sm_scale=sm_scale, use_kernel=use_kernel,
                              window=window)

    return jax.jit(shard_map_fn(mesh, local, in_specs=(spec, spec, spec), out_specs=spec))
