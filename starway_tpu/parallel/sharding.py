"""Mesh + sharding helpers.

The recipe (scaling-book style): pick a mesh, annotate shardings, let XLA
insert the collectives.  These helpers keep mesh construction and
NamedSharding spelling in one place for the rest of the framework.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from ``{"dp": 2, "tp": 2, "sp": 2}``-style axis sizes.

    Axis order follows dict order; sizes must multiply to the device count
    used.  On TPU hardware the trailing axes map to the fastest ICI
    neighborhoods, so put the most communication-heavy axis (tp/sp) last.
    """
    devs = list(devices) if devices is not None else jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, only {len(devs)} available")
    grid = np.array(devs[:n]).reshape(shape)
    return Mesh(grid, tuple(axes.keys()))


def mesh_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding shorthand: mesh_sharding(mesh, 'dp', None, 'tp')."""
    return NamedSharding(mesh, P(*spec))


def shard_array(mesh: Mesh, x, *spec):
    return jax.device_put(x, mesh_sharding(mesh, *spec))


def shard_map_fn(mesh: Mesh, fn, in_specs, out_specs):
    """Version-tolerant shard_map wrapper (per-device SPMD view)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.7 style

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm  # legacy

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
