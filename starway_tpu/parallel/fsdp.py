"""ZeRO-style fully-sharded data parallelism via GSPMD sharding annotations.

TPU-first FSDP is declarative: shard every parameter (and its optimizer
state) along a mesh axis, jit the train step with those shardings, and XLA
inserts the all-gather before each use and the reduce-scatter after the
backward — the ZeRO-3 communication schedule, scheduled and overlapped by
the compiler instead of hand-written bucketing hooks.  (Scaling-book
recipe; the reference — Clouder0/starway — has no training layer at all,
so this module is part of the TPU build's own SPMD surface, alongside
dp_exchange.py's P2P gradient exchange which mirrors how the reference's
primitives would be composed: /root/reference/benchmark.md:91-99.)

Mechanics:

* :func:`fsdp_specs` maps any pytree of arrays/shapes to PartitionSpecs,
  sharding the largest divisible dimension of each leaf over ``axis``
  (skipping dims already taken by a base spec, e.g. llama's tp specs —
  giving the hybrid FSDP×TP layout).  Stacked-layer params ``[L, ...]``
  (ndim >= 3 by this repo's convention) never shard the leading layer dim:
  the forward ``lax.scan``s over it, and sharding it would turn every scan
  slice into a cross-device dynamic-slice instead of a local one.
* The same rule applied to ``jax.eval_shape(tx.init, params)`` shards
  Adam's mu/nu exactly like their parameters (same shapes), which is what
  makes this ZeRO and not just sharded matmuls: each device holds 1/N of
  the master optimizer state.
* :func:`make_fsdp_train_step` jits the ordinary train step with those
  in/out shardings; donation keeps params+opt in place in HBM.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _stacked_layer_rule(shape) -> int:
    """Dims to protect at the front: 1 for stacked-layer leaves (ndim >= 3,
    the [n_layers, ...] convention used across models/), else 0."""
    return 1 if len(shape) >= 3 else 0


def _leaf_spec(shape, base, axis: str, n: int, skip: int) -> P:
    """Shard the largest dim of ``shape`` divisible by ``n`` over ``axis``,
    keeping any dims already sharded by ``base`` untouched."""
    entries = [None] * len(shape)
    if base is not None:
        for i, e in enumerate(base):
            if i < len(entries):
                entries[i] = e
    candidates = [
        (dim, i)
        for i, dim in enumerate(shape)
        if entries[i] is None and i >= skip and dim % n == 0 and dim >= n
    ]
    if candidates:
        _, i = max(candidates)
        entries[i] = axis
    return P(*entries)


def fsdp_specs(tree, mesh: Mesh, *, axis: str = "fsdp", base_specs=None,
               skip_leading: Union[int, Callable] = _stacked_layer_rule):
    """PartitionSpec tree sharding each leaf's largest free dim over ``axis``.

    ``tree`` may hold arrays or ShapeDtypeStructs (so it works on
    ``jax.eval_shape(tx.init, params)`` for optimizer state).  ``base_specs``
    (same tree structure, e.g. llama's tp ``param_specs``) pins dims that
    must keep their existing sharding; pass it only when ``axis`` coexists
    with those axes on one mesh.  Leaves with no dim divisible by the axis
    size stay replicated — correct, just not memory-sharded (scalars,
    odd-sized norms).  ``skip_leading`` protects leading dims from being
    chosen: an int, or a callable ``shape -> int`` (default: skip the
    stacked-layer dim of ndim>=3 leaves).
    """
    n = mesh.shape[axis]
    skip_fn = skip_leading if callable(skip_leading) else (lambda _s: skip_leading)
    base_leaves = None
    if base_specs is not None:
        base_leaves = jax.tree_util.tree_leaves(
            base_specs, is_leaf=lambda x: isinstance(x, P))
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if base_leaves is not None and len(base_leaves) != len(leaves):
        raise ValueError(
            f"base_specs has {len(base_leaves)} leaves, tree has {len(leaves)}")

    specs = []
    for i, leaf in enumerate(leaves):
        shape = tuple(leaf.shape)
        if not shape:
            specs.append(P())
            continue
        base = base_leaves[i] if base_leaves is not None else None
        skip = min(skip_fn(shape), len(shape) - 1)
        specs.append(_leaf_spec(shape, base, axis, n, skip))
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_tree(tree, mesh: Mesh, specs):
    """device_put every leaf onto its NamedSharding."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    spec_flat = jax.tree_util.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    out = [jax.device_put(x, NamedSharding(mesh, s)) for x, s in zip(flat, spec_flat)]
    return jax.tree_util.tree_unflatten(treedef, out)


def make_fsdp_train_step(train_step, mesh: Mesh, param_specs, opt_specs,
                         *, axis: str = "fsdp",
                         batch_spec: Optional[P] = None,
                         donate: bool = True):
    """jit ``train_step(params, opt_state, batch)`` with ZeRO shardings.

    Params and optimizer state live sharded per ``param_specs``/``opt_specs``
    and by default are donated (updated in place in HBM — the caller's input
    arrays are consumed; pass ``donate=False`` to keep them alive at the
    cost of a copy).  The batch shards its leading dim over ``axis`` unless
    ``batch_spec`` overrides it (FSDP is still data parallelism).  XLA's
    SPMD partitioner materialises each layer's weights via all-gather
    just-in-time inside the scan and reduce-scatters gradients straight
    into the sharded optimizer update.
    """
    if batch_spec is None:
        batch_spec = P(axis)

    def sh(specs):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

    return jax.jit(
        train_step,
        in_shardings=(sh(param_specs), sh(opt_specs),
                      NamedSharding(mesh, batch_spec)),
        out_shardings=(sh(param_specs), sh(opt_specs), None),
        donate_argnums=(0, 1) if donate else (),
    )
