"""Interleaved 1F1B: virtual pipeline stages (Megatron-style chunks).

The plain 1F1B schedule (parallel/pipeline.py) has bubble fraction
``2(S-1) / (M + 2(S-1))`` — painful at small microbatch counts.  Splitting
the model into ``V`` chunks per device (virtual stages) shortens the
pipeline fill to one CHUNK's flight instead of one fused device-stage's.

**Honest accounting for this executor.**  Both schedules here are
masked-slot SPMD programs: every scan tick executes one F and one B slot
on every device whether or not the slot is live, so an idle slot costs
wall clock (unlike an eager executor, where Megatron's full ``V``× bubble
shrink applies).  Under that model the greedy schedule below sits ON the
critical-path lower bound (device-0 F throughput + the last microbatch's
2VS-hop chain), and the win over plain 1F1B — same V*S-layer model, same
devices, ticks normalised to chunk-passes — is ``(V-1)(S-2)`` ticks
(for M >= S; below that both schedules tie at the shared critical path):
``V(M + 2(S-1))`` plain vs ``VM + VS + S - 2`` interleaved.  ~7-10% at
(V=2, S=4), ~20% at (V=4, S=8), nothing at S=2 — worth it exactly when
stages are many and microbatches few.

Design (TPU/SPMD-first, not a port of Megatron's executor):

* **Placement**: virtual stage ``v`` of ``n_virtual = V*S`` lives on device
  ``v % S`` as its chunk ``v // S``.  Consecutive virtual stages therefore
  sit on consecutive devices — every activation hop is the SAME uniform
  ring ``ppermute`` the non-interleaved pipeline uses; wraps (device S-1 →
  device 0 forward, device 0 → device S-1 backward) carry the flow into
  the next chunk.
* **The schedule is two injection sequences.**  Within a chunk, a
  microbatch moves one device per tick (no stalls), so every F slot is
  determined by the tick its (chunk, microbatch) ENTERED device 0
  (``entry0``), and every B slot by the tick its backward entered device
  S-1 (``binj``).  Both sequences are built (and verified) on the HOST at
  trace time; the device program is a ``lax.scan`` that executes
  precomputed per-tick slot tables — no data-dependent control flow.
* **Stash & inbox are table-indexed.**  Stage inputs stash per chunk for
  the backward remat (free-list slots assigned host-side); backward
  wrap cotangents queue in a per-chunk ring whose read/write positions
  are also baked into the tables.  Forward wraps need NO queue: exact
  ``S``-spacing of chunk entries makes every wrap consumed the tick it
  arrives.

Same homogeneous-stage constraint as the base schedule; embedding/head
live outside (models/pp_llama.py shows the pattern for the base
schedule).  Gradient parity vs the sequential VS-stage chain is pinned by
tests/test_interleaved.py.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .sharding import shard_map_fn


@dataclasses.dataclass(frozen=True)
class InterleavedSchedule:
    """Host-built slot program for one (M, S, V).  All arrays [ticks, S]
    int32 unless noted; -1 = no slot this tick."""

    n_micro: int
    n_devices: int
    n_chunks: int
    ticks: int
    stash_depth: int      # per-chunk stash slots
    inbox_depth: int      # per-chunk backward wrap-queue slots
    # F slot: chunk, microbatch, stash slot to write, inject? (device 0
    # chunk 0 reads inputs[i]; every other F consumes the fwd ring carry).
    f_chunk: np.ndarray
    f_micro: np.ndarray
    f_stash: np.ndarray
    f_inject: np.ndarray  # bool [ticks, S]
    # B slot: chunk, microbatch, stash slot to read, final? (loss vjp),
    # wrap-inbox read position (-1 = take the bwd ring carry).
    b_chunk: np.ndarray
    b_micro: np.ndarray
    b_stash: np.ndarray
    b_final: np.ndarray   # bool
    b_inbox_rd: np.ndarray
    # Backward wrap WRITE: where tick t's incoming bwd ppermute lands
    # (only ever valid on device S-1): chunk, ring position.
    w_chunk: np.ndarray
    w_pos: np.ndarray


def build_interleaved_schedule(m: int, s: int, v: int) -> InterleavedSchedule:
    """Build + verify the slot program (pure numpy, cache-friendly args)."""
    if m < 1 or s < 1 or v < 1:
        raise ValueError(f"need m,s,v >= 1, got {(m, s, v)}")

    # ---- forward injections at device 0: groups of up to S microbatches,
    # chunk-major inside a group, stride V*S per full group.  Spacing of a
    # microbatch's chunk entries is EXACTLY S -> wraps consumed on arrival.
    entry0 = np.zeros((v, m), np.int64)
    base = 0
    for g0 in range(0, m, s):
        gsz = min(s, m - g0)
        for c in range(v):
            for i in range(gsz):
                entry0[c, g0 + i] = base + c * s + i
        base += v * s  # uniform stride, even for a partial last group

    # ---- backward injections at device S-1: greedy, lowest chunk first
    # (drain depth-first frees stash earliest).  Chunk V-1 of microbatch j
    # becomes ready the tick its forward REACHES device S-1 (the loss-vjp
    # slot recomputes from the stash written that same tick); chunk c < V-1
    # becomes ready when chunk c+1's backward wrap ARRIVES
    # (binj(c+1) + S-1 done at device 0, +1 for the hop).
    f_done = entry0 + (s - 1)          # F tick at device S-1 per (c, j)
    binj = -np.ones((v, m), np.int64)
    ready = {(v - 1, j): int(f_done[v - 1, j]) for j in range(m)}
    t = 0
    remaining = v * m
    # The loop injects at most one backward per tick, so it NEEDS ~v*m
    # ticks; the horizon must scale with that (a bound in m alone
    # spuriously failed valid v >= 5 configs at large m).
    horizon = (v * m + v * s * max(v, s) + 64) * 4
    while remaining and t < horizon:
        # one backward injection per tick max (device S-1's single B slot)
        cand = [(c, j) for (c, j), rt in ready.items() if rt <= t]
        if cand:
            c, j = min(cand, key=lambda cj: (cj[0], cj[1]))
            del ready[(c, j)]
            binj[c, j] = t
            remaining -= 1
            if c > 0:
                # wrap finishes the chunk at device 0 at t + S-1, arrives
                # back at device S-1 next tick.
                ready[(c - 1, j)] = t + s
        t += 1
    if remaining:
        raise RuntimeError("interleaved schedule failed to converge "
                           f"(m={m}, s={s}, v={v})")

    ticks = int(max(binj.max() + s, entry0.max() + s))

    # ---- per-device slot tables --------------------------------------
    f_chunk = -np.ones((ticks, s), np.int32)
    f_micro = -np.ones((ticks, s), np.int32)
    f_inject = np.zeros((ticks, s), bool)
    b_chunk = -np.ones((ticks, s), np.int32)
    b_micro = -np.ones((ticks, s), np.int32)
    b_final = np.zeros((ticks, s), bool)
    for c in range(v):
        for i in range(m):
            for d in range(s):
                tf = int(entry0[c, i]) + d
                assert f_chunk[tf, d] == -1, "F slot collision"
                f_chunk[tf, d] = c
                f_micro[tf, d] = i
                f_inject[tf, d] = (d == 0 and c == 0)
                tb = int(binj[c, i]) + (s - 1 - d)
                assert b_chunk[tb, d] == -1, "B slot collision"
                b_chunk[tb, d] = c
                b_micro[tb, d] = i
                b_final[tb, d] = (d == s - 1 and c == v - 1)

    # ---- stash slots: free-list per (device is uniform: F at device d is
    # entry0+d, B at binj+(s-1-d); the in-flight WINDOW is widest at
    # device 0 for F / also fine to compute per device and take the max).
    stash_sl = -np.ones((ticks, s), np.int32)   # slot written by F
    stash_rd = -np.ones((ticks, s), np.int32)   # slot read by B
    depth = 0
    for d in range(s):
        slot_of = {}
        free: list = []
        next_new = 0
        for t in range(ticks):
            if f_chunk[t, d] >= 0:
                key = (int(f_chunk[t, d]), int(f_micro[t, d]))
                if free:
                    sl = free.pop()
                else:
                    sl = next_new
                    next_new += 1
                slot_of[key] = sl
                stash_sl[t, d] = sl
            if b_chunk[t, d] >= 0:
                key = (int(b_chunk[t, d]), int(b_micro[t, d]))
                sl = slot_of.pop(key)
                stash_rd[t, d] = sl
                free.append(sl)
        depth = max(depth, next_new)

    # ---- backward wrap inbox (device S-1 only): a B of chunk c>=1 done
    # at device 0 at tick t lands at device S-1 at t+1 for chunk c-1;
    # consumed at binj[c-1, j].  FIFO ring per chunk, positions baked in.
    w_chunk = -np.ones((ticks, s), np.int32)
    w_pos = -np.ones((ticks, s), np.int32)
    b_inbox_rd = -np.ones((ticks, s), np.int32)
    inbox_depth = 1
    wr = np.zeros(v, np.int64)
    rd = np.zeros(v, np.int64)
    pos_of = {}
    for t in range(ticks):
        # arrival first (ppermute from the previous tick's device-0 B)...
        if t > 0 and b_chunk[t - 1, 0] >= 1:
            c_arr = int(b_chunk[t - 1, 0]) - 1
            w_chunk[t, s - 1] = c_arr  # position filled in the second pass
            pos_of[(c_arr, int(b_micro[t - 1, 0]))] = int(wr[c_arr])
            wr[c_arr] += 1
        # ...then consumption by this tick's B slot at device S-1.
        if b_chunk[t, s - 1] >= 0 and not b_final[t, s - 1]:
            c = int(b_chunk[t, s - 1])
            j = int(b_micro[t, s - 1])
            if c == v - 1:
                raise AssertionError("non-final B at chunk V-1, device S-1")
            abs_pos = pos_of.pop((c, j))
            assert abs_pos == rd[c], "inbox consumed out of FIFO order"
            b_inbox_rd[t, s - 1] = abs_pos  # ring-reduced after sizing
            rd[c] += 1
            inbox_depth = max(inbox_depth, int((wr - rd).max()) + 1)
    # size the ring, then assign positions modulo the final depth
    wr = np.zeros(v, np.int64)
    for t in range(ticks):
        if w_chunk[t, s - 1] >= 0:
            w_pos[t, s - 1] = int(wr[w_chunk[t, s - 1]] % inbox_depth)
            wr[w_chunk[t, s - 1]] += 1
    rd = np.zeros(v, np.int64)
    for t in range(ticks):
        if b_inbox_rd[t, s - 1] >= 0:
            c = int(b_chunk[t, s - 1])
            b_inbox_rd[t, s - 1] = int(rd[c] % inbox_depth)
            rd[c] += 1

    return InterleavedSchedule(
        n_micro=m, n_devices=s, n_chunks=v, ticks=ticks,
        stash_depth=max(depth, 1), inbox_depth=inbox_depth,
        f_chunk=f_chunk, f_micro=f_micro, f_stash=stash_sl,
        f_inject=f_inject, b_chunk=b_chunk, b_micro=b_micro,
        b_stash=stash_rd, b_final=b_final, b_inbox_rd=b_inbox_rd,
        w_chunk=w_chunk, w_pos=w_pos,
    )


def interleaved_train_apply(stage_fn: Callable, loss_fn: Callable,
                            stage_params, inputs, targets, axis_name: str,
                            sched: InterleavedSchedule, head_params=None,
                            return_dx: bool = False, with_aux: bool = False):
    """Per-device body (call inside shard_map).

    ``stage_params``: this device's chunks, leading dim V (chunk c =
    virtual stage ``c*S + d``).  ``inputs [M, mb, ...]`` / ``targets
    [M, ...]`` replicated.  Returns ``(loss, dparams [V, ...][, dhead]
    [, dinputs])`` laid out like the params — the same contract as
    ``pipeline_train_apply``: ``head_params`` makes the final slot's loss
    ``loss_fn(head_params, y, target)`` (head gradient psum-replicated);
    ``return_dx`` emits ``[1, M, mb, ...]`` input cotangents valid on
    device 0's shard only (chunk-0 backwards).  ``with_aux``:
    ``stage_fn`` returns ``(y, aux)`` and every virtual stage's scalar
    aux joins loss and gradients exactly as in
    :func:`~starway_tpu.parallel.pipeline.pipeline_train_apply` — F-slot
    value accumulation (the LAST virtual stage excluded: its aux joins
    the final slot's loss closure), cotangent-1 seeding in mid-chunk
    backward vjps.
    """
    s = sched.n_devices
    v = sched.n_chunks
    m = sched.n_micro
    d_idx = lax.axis_index(axis_name)
    mb_shape = inputs.shape[1:]
    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    bwd_perm = [(i, (i - 1) % s) for i in range(s)]

    tabs = {k: jnp.asarray(getattr(sched, k)) for k in (
        "f_chunk", "f_micro", "f_stash", "f_inject", "b_chunk", "b_micro",
        "b_stash", "b_final", "b_inbox_rd", "w_chunk", "w_pos")}

    def pick(tab_row):
        return tab_row[d_idx]

    def chunk_params(c):
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, c, axis=0, keepdims=False),
            stage_params)

    def f32_zeros_like(tree):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, jnp.float32), tree)

    def apply_stage(p, x):
        out = stage_fn(p, x)
        return out if with_aux else (out, jnp.float32(0))

    def tick(carry, trow):
        fwd_in, bwd_in, stash, inbox, dparams, dhead, dx_buf, loss_acc = carry
        fc = pick(trow["f_chunk"])
        fi = pick(trow["f_micro"])
        fsl = pick(trow["f_stash"])
        finj = pick(trow["f_inject"])
        bc = pick(trow["b_chunk"])
        bj = pick(trow["b_micro"])
        bsl = pick(trow["b_stash"])
        bfin = pick(trow["b_final"])
        brd = pick(trow["b_inbox_rd"])
        wc = pick(trow["w_chunk"])
        wp = pick(trow["w_pos"])

        # ---- backward wrap arrival (device S-1): file last tick's
        # incoming cotangent into the per-chunk ring before any use.
        wc_c = jnp.clip(wc, 0, v - 1)
        wp_c = jnp.clip(wp, 0, sched.inbox_depth - 1)
        upd = jnp.where(wc >= 0, bwd_in,
                        inbox[wc_c, wp_c])  # no-op write when invalid
        inbox = lax.dynamic_update_index_in_dim(
            inbox, lax.dynamic_update_index_in_dim(
                inbox[wc_c], upd, wp_c, axis=0), wc_c, axis=0)

        # ---- F slot ----------------------------------------------------
        f_valid = fc >= 0
        fc_c = jnp.clip(fc, 0, v - 1)
        x_inject = inputs[jnp.clip(fi, 0, m - 1)]
        x = jnp.where(finj, x_inject, fwd_in)
        y, aux_f = apply_stage(chunk_params(fc_c), x)
        # Aux VALUE: once per (virtual stage, microbatch) in the F slot;
        # the final virtual stage (this device's last chunk on the last
        # device) is excluded — its aux joins the final slot's loss_j.
        last_vstage = (d_idx == s - 1) & (fc_c == v - 1)
        loss_acc = loss_acc + jnp.where(f_valid & ~last_vstage, aux_f, 0.0)
        sl = jnp.where(f_valid, jnp.clip(fsl, 0, sched.stash_depth - 1),
                       sched.stash_depth)  # trash slot
        stash = lax.dynamic_update_index_in_dim(stash, x, sl, axis=0)
        fwd_out = lax.ppermute(y.astype(inputs.dtype), axis_name, fwd_perm)

        # ---- B slot ----------------------------------------------------
        b_valid = bc >= 0
        bc_c = jnp.clip(bc, 0, v - 1)
        bj_c = jnp.clip(bj, 0, m - 1)
        x_saved = stash[jnp.clip(bsl, 0, sched.stash_depth - 1)]
        target = targets[bj_c]
        # Incoming cotangent: the ring carry (within-chunk hop) unless the
        # tables point at an inbox position (device S-1 wrap consumption).
        ct_in = jnp.where(brd >= 0,
                          inbox[bc_c, jnp.clip(brd, 0, sched.inbox_depth - 1)],
                          bwd_in)
        p_c = chunk_params(bc_c)

        def final_branch(_):
            if head_params is None:
                def h(p, x):
                    yy, aa = apply_stage(p, x)
                    return loss_fn(yy, target) + aa

                loss_j, (dp, dx) = jax.value_and_grad(h, argnums=(0, 1))(
                    p_c, x_saved)
                dh = dhead  # zeros-shaped placeholder, unused
            else:
                def h(p, x, hp):
                    yy, aa = apply_stage(p, x)
                    return loss_fn(hp, yy, target) + aa

                loss_j, (dp, dx, dh) = jax.value_and_grad(
                    h, argnums=(0, 1, 2))(p_c, x_saved, head_params)
                dh = f32_tree(dh)
            return (f32_tree(dp), dx.astype(jnp.float32), dh,
                    jnp.asarray(loss_j, jnp.float32))

        def mid_branch(_):
            (yy, aa), vjp_fn = jax.vjp(apply_stage, p_c, x_saved)
            dp, dx = vjp_fn((ct_in.astype(yy.dtype),
                             jnp.ones((), aa.dtype)))
            return (f32_tree(dp), dx.astype(jnp.float32),
                    f32_zeros_like(head_params), jnp.float32(0))

        def f32_tree(tree):
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32), tree)

        dp, dx, dh, loss_j = lax.cond(bfin, final_branch, mid_branch, None)
        mask = b_valid.astype(jnp.float32)
        dparams = jax.tree_util.tree_map(
            lambda acc, g: acc.at[bc_c].add(mask * g), dparams, dp)
        if head_params is not None:
            dhead = jax.tree_util.tree_map(
                lambda acc, g: acc + mask * g, dhead, dh)
        loss_acc = loss_acc + mask * loss_j
        if return_dx:
            # Chunk-0 backwards on device 0 ARE d(inputs); everything else
            # (other chunks, other devices, invalid slots) lands in the
            # trash row m — interleaving means real writes and dead slots
            # interleave in time, so masking by slot index (not a
            # write-zeros policy) is what keeps earlier real values intact.
            is_dx = (d_idx == 0) & (bc == 0) & b_valid
            dx_buf = lax.dynamic_update_index_in_dim(
                dx_buf, dx * mask, jnp.where(is_dx, bj_c, m), axis=0)
        bwd_out = lax.ppermute(dx * mask, axis_name, bwd_perm)

        return (fwd_out, bwd_out, stash, inbox, dparams, dhead, dx_buf,
                loss_acc), None

    init = (
        jnp.zeros(mb_shape, inputs.dtype),
        jnp.zeros(mb_shape, jnp.float32),
        jnp.zeros((sched.stash_depth + 1,) + mb_shape, inputs.dtype),
        jnp.zeros((v, sched.inbox_depth) + mb_shape, jnp.float32),
        f32_zeros_like(stage_params),
        f32_zeros_like(head_params),
        jnp.zeros((m + 1,) + mb_shape, jnp.float32) if return_dx
        else jnp.zeros((), jnp.float32),
        jnp.float32(0),
    )
    (_, _, _, _, dparams, dhead, dx_buf, loss_acc), _ = lax.scan(
        tick, init, tabs)
    loss = lax.psum(loss_acc, axis_name) / m
    dparams = jax.tree_util.tree_map(lambda g: g / m, dparams)
    out = (loss, dparams)
    if head_params is not None:
        dhead = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name) / m, dhead)
        out += (dhead,)
    if return_dx:
        out += (dx_buf[None, :m] / m,)  # [1, M, mb, ...]: this device's shard
    return out


def make_interleaved_pipeline_train(mesh, stage_fn: Callable,
                                    loss_fn: Callable,
                                    axis_name: str = "pp", *,
                                    n_chunks: int, n_micro: int,
                                    with_head: bool = False,
                                    return_dx: bool = False,
                                    dp_axis: str | None = None,
                                    with_aux: bool = False):
    """Jitted global-view interleaved-1F1B training step builder.

    ``stage_params`` global view: ``[V, S, ...]`` — ``stage_params[c, d]``
    is virtual stage ``c*S + d`` (device d's chunk c); dim 1 shards over
    ``axis_name``.  Returns ``step(stage_params[, head_params], inputs,
    targets) -> (loss, grads[, dhead][, dinputs])`` with grads laid out
    like the params — ``with_head``/``return_dx``/``dp_axis`` follow
    :func:`~starway_tpu.parallel.pipeline.make_pipeline_train`'s contract
    (dinputs from device 0's shard; under dp, the within-microbatch batch
    dim of inputs/targets shards over ``dp_axis``, loss/grads ride one dp
    pmean, and dinputs carry the 1/ndp factor).  ``n_micro`` is static
    (the slot tables are built for it); inputs [M, mb, ...].
    """
    from .pipeline import dp_compose

    s = mesh.shape[axis_name]
    sched = build_interleaved_schedule(n_micro, s, n_chunks)
    data_spec, dx_spec, dp_reduce = dp_compose(
        mesh, dp_axis, axis_name, with_head=with_head, return_dx=return_dx)

    def peel(tree):
        # shard_map leaves a size-1 device dim at axis 1: [V, 1, ...] ->
        # [V, ...]
        return jax.tree_util.tree_map(lambda a: a[:, 0], tree)

    def unpeel(tree):
        return jax.tree_util.tree_map(lambda a: a[:, None], tree)

    if with_head:
        def local(stage_params, head_params, inputs, targets):
            out = interleaved_train_apply(
                stage_fn, loss_fn, peel(stage_params), inputs, targets,
                axis_name, sched, head_params=head_params,
                return_dx=return_dx, with_aux=with_aux)
            out = dp_reduce(out)
            return (out[0], unpeel(out[1])) + out[2:]

        in_specs = (P(None, axis_name), P(), data_spec, data_spec)
        out_specs = (P(), P(None, axis_name), P()) + (
            (dx_spec,) if return_dx else ())
    else:
        def local(stage_params, inputs, targets):
            out = interleaved_train_apply(
                stage_fn, loss_fn, peel(stage_params), inputs, targets,
                axis_name, sched, return_dx=return_dx, with_aux=with_aux)
            out = dp_reduce(out)
            return (out[0], unpeel(out[1])) + out[2:]

        in_specs = (P(None, axis_name), data_spec, data_spec)
        out_specs = (P(), P(None, axis_name)) + (
            (dx_spec,) if return_dx else ())

    staged = shard_map_fn(mesh, local, in_specs=in_specs,
                          out_specs=out_specs)
    if not return_dx:
        return jax.jit(staged)

    def run(*args):
        out = staged(*args)
        return out[:-1] + (out[-1][0],)  # dinputs lives on device 0's shard

    return jax.jit(run)
