"""DP-boundary pytree transfer over the async P2P API.

BASELINE config 5: "Llama-3 8B activation/grad transfer between TPU hosts
(DP boundary)".  The unit of exchange is a pytree of jax.Arrays (a gradient
tree, an activation dict); each leaf becomes one tagged message, tags are
``base_tag + leaf_index``, and a flush closes the batch -- the same shape a
user of the reference would build by hand from asend/arecv
(SURVEY.md section 2, BASELINE configs).

Ports unify the two directions of the Client/Server API so the same transfer
code runs on either side:

>>> await send_pytree(ClientPort(client), grads, base_tag=0x50000)
>>> grads2 = await recv_pytree(ServerPort(server), like=grads, base_tag=0x50000)
"""

from __future__ import annotations

from typing import Any

import jax

from ..device import DeviceBuffer

FULL_MASK = (1 << 64) - 1


class ClientPort:
    """Client side of a duplex link."""

    def __init__(self, client):
        self._c = client

    def asend(self, buf, tag):
        return self._c.asend(buf, tag)

    def arecv(self, buf, tag, mask=FULL_MASK):
        return self._c.arecv(buf, tag, mask)

    def aflush(self):
        return self._c.aflush()


class ServerPort:
    """Server side of a duplex link.

    Sends are bound to one endpoint; receives are worker-wide tag matches
    (the core contract -- reference recvs post on the worker, not the
    endpoint, src/bindings/main.cpp:1172).  With multiple peers exchanging
    concurrently, give each peer a disjoint ``base_tag`` range (note the Trainer's DP
    exchange occupies ``[dp_base_tag, dp_base_tag + 0x40000)``); tags are the
    routing key, exactly as in the reference's multi-client fan-in pattern
    (tests/test_basic.py:526-554)."""

    def __init__(self, server, endpoint=None):
        self._s = server
        if endpoint is None:
            clients = server.list_clients()
            if not clients:
                raise ValueError("server has no connected endpoints")
            endpoint = next(iter(clients))
        self._ep = endpoint

    def asend(self, buf, tag):
        return self._s.asend(self._ep, buf, tag)

    def arecv(self, buf, tag, mask=FULL_MASK):
        return self._s.arecv(buf, tag, mask)

    def aflush(self):
        return self._s.aflush_ep(self._ep)


async def send_pytree(port, tree: Any, base_tag: int, *, flush: bool = True) -> int:
    """Send every leaf of ``tree`` as a tagged message; returns leaf count.

    Leaves go out concurrently (the engine pipelines them); ``flush=True``
    appends the delivery barrier so the batch survives a subsequent close.
    """
    import asyncio

    leaves = jax.tree_util.tree_leaves(tree)
    await asyncio.gather(*(port.asend(leaf, base_tag + i) for i, leaf in enumerate(leaves)))
    if flush:
        await port.aflush()
    return len(leaves)


async def recv_pytree(port, like: Any, base_tag: int, *, device=None) -> Any:
    """Receive a pytree shaped like ``like``; returns the reconstructed tree
    of received jax.Arrays (placed on ``device`` or each leaf's own device)."""
    import asyncio

    leaves, treedef = jax.tree_util.tree_flatten(like)
    sinks = [
        DeviceBuffer.like(leaf, device=device) for leaf in leaves
    ]
    await asyncio.gather(
        *(port.arecv(sink, base_tag + i) for i, sink in enumerate(sinks))
    )
    return jax.tree_util.tree_unflatten(treedef, [s.array for s in sinks])
