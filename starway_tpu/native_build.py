"""On-demand build of the native engine shared library.

No pip/pybind11 in the image, so the extension is a plain shared object
compiled with g++ and driven through ctypes.  Built lazily into the package
directory; rebuilt when the source is newer than the artifact.  Every build
attempt's compiler output is captured to ``_sw_native.build.log`` next to
the artifact, and failures raise with the output tail + the log path, so a
broken toolchain is diagnosable from the exception alone.
"""

from __future__ import annotations

import subprocess
from pathlib import Path

_PKG_DIR = Path(__file__).parent
_SRC = _PKG_DIR.parent / "native" / "sw_engine.cpp"
_HDR = _PKG_DIR.parent / "native" / "sw_engine.h"
_OUT = _PKG_DIR / "_sw_native.so"
_LOG = _PKG_DIR / "_sw_native.build.log"

_BUILD_TIMEOUT_S = 300


def prebuilt() -> "Path | None":
    """The existing artifact if present and fresh, else None — NEVER
    compiles.  For callers on latency-sensitive paths (connection setup)
    that want the lib only if it is already there."""
    if not _SRC.exists() and not _HDR.exists():
        # Installed wheel: NO native/ sources ship, but the built engine
        # does (pyproject package-data).  The bundled artifact IS current.
        # (Exactly one source missing is a broken checkout, not a wheel —
        # fall through so staleness/raise behaviour applies.)
        return _OUT if _OUT.exists() else None
    if (_SRC.exists() and _HDR.exists() and _OUT.exists()
            and _OUT.stat().st_mtime >= max(_SRC.stat().st_mtime,
                                            _HDR.stat().st_mtime)):
        return _OUT
    return None


def _capture_log(cmd: list, stdout, stderr) -> str:
    """Write the build transcript to _LOG (best-effort) and return the
    combined output tail for embedding in the raised error."""
    def _text(x) -> str:
        if x is None:
            return ""
        if isinstance(x, bytes):
            return x.decode(errors="replace")
        return x

    out, err = _text(stdout), _text(stderr)
    body = f"$ {' '.join(cmd)}\n--- stdout ---\n{out}\n--- stderr ---\n{err}\n"
    try:
        _LOG.write_text(body)
    except OSError:
        pass  # read-only install dir: the tail in the exception still helps
    tail = (out + "\n" + err).strip()
    return tail[-4000:]


def ensure_built(force: bool = False) -> Path:
    """Compile native/sw_engine.cpp -> starway_tpu/_sw_native.so if stale.

    Builds to a per-process temp path and atomically renames into place, so
    concurrent ranks/test workers never load a half-written artifact.
    """
    import os

    if not _SRC.exists() or not _HDR.exists():
        if not _SRC.exists() and not _HDR.exists() and _OUT.exists():
            # Installed wheel: sources absent, bundled artifact present.
            # One source missing is a broken checkout — raise below, and
            # never serve a stale artifact against new-protocol peers.
            return _OUT
        missing = _SRC if not _SRC.exists() else _HDR
        raise FileNotFoundError(f"native source missing: {missing}")
    src_mtime = max(_SRC.stat().st_mtime, _HDR.stat().st_mtime)
    if not force and _OUT.exists() and _OUT.stat().st_mtime >= src_mtime:
        return _OUT
    tmp = _OUT.with_suffix(f".tmp.{os.getpid()}.so")
    # -lrt: shm_open/shm_unlink live in librt on glibc < 2.34 (harmless
    # no-op link on newer glibc, where librt is a stub).
    cmd = [
        "g++", "-std=c++20", "-O2", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wextra",
        str(_SRC), "-o", str(tmp), "-lrt",
    ]
    try:
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=_BUILD_TIMEOUT_S)
        except subprocess.TimeoutExpired as e:
            tail = _capture_log(cmd, e.stdout, e.stderr)
            raise RuntimeError(
                f"native build timed out after {_BUILD_TIMEOUT_S}s "
                f"(log: {_LOG})\n{tail}"
            ) from e
        except OSError as e:  # g++ missing / not executable
            raise RuntimeError(
                f"native build could not start ({e}); is a C++ toolchain "
                f"installed? (cmd: {' '.join(cmd)})"
            ) from e
        if proc.returncode != 0:
            tail = _capture_log(cmd, proc.stdout, proc.stderr)
            raise RuntimeError(
                f"native build failed with exit code {proc.returncode} "
                f"(log: {_LOG})\n{tail}"
            )
        _capture_log(cmd, proc.stdout, proc.stderr)  # keep the success log too
        os.replace(tmp, _OUT)
    finally:
        tmp.unlink(missing_ok=True)
    return _OUT


if __name__ == "__main__":
    print(ensure_built(force=True))
