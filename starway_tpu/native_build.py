"""On-demand build of the native engine shared library.

No pip/pybind11 in the image, so the extension is a plain shared object
compiled with g++ and driven through ctypes.  Built lazily into the package
directory; rebuilt when the source is newer than the artifact.
"""

from __future__ import annotations

import subprocess
from pathlib import Path

_PKG_DIR = Path(__file__).parent
_SRC = _PKG_DIR.parent / "native" / "sw_engine.cpp"
_HDR = _PKG_DIR.parent / "native" / "sw_engine.h"
_OUT = _PKG_DIR / "_sw_native.so"


def prebuilt() -> "Path | None":
    """The existing artifact if present and fresh, else None — NEVER
    compiles.  For callers on latency-sensitive paths (connection setup)
    that want the lib only if it is already there."""
    if not _SRC.exists() and not _HDR.exists():
        # Installed wheel: NO native/ sources ship, but the built engine
        # does (pyproject package-data).  The bundled artifact IS current.
        # (Exactly one source missing is a broken checkout, not a wheel —
        # fall through so staleness/raise behaviour applies.)
        return _OUT if _OUT.exists() else None
    if (_SRC.exists() and _HDR.exists() and _OUT.exists()
            and _OUT.stat().st_mtime >= max(_SRC.stat().st_mtime,
                                            _HDR.stat().st_mtime)):
        return _OUT
    return None


def ensure_built(force: bool = False) -> Path:
    """Compile native/sw_engine.cpp -> starway_tpu/_sw_native.so if stale.

    Builds to a per-process temp path and atomically renames into place, so
    concurrent ranks/test workers never load a half-written artifact.
    """
    import os

    if not _SRC.exists() or not _HDR.exists():
        if not _SRC.exists() and not _HDR.exists() and _OUT.exists():
            # Installed wheel: sources absent, bundled artifact present.
            # One source missing is a broken checkout — raise below, and
            # never serve a stale artifact against new-protocol peers.
            return _OUT
        missing = _SRC if not _SRC.exists() else _HDR
        raise FileNotFoundError(f"native source missing: {missing}")
    src_mtime = max(_SRC.stat().st_mtime, _HDR.stat().st_mtime)
    if not force and _OUT.exists() and _OUT.stat().st_mtime >= src_mtime:
        return _OUT
    tmp = _OUT.with_suffix(f".tmp.{os.getpid()}.so")
    cmd = [
        "g++", "-std=c++20", "-O2", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wextra",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            raise RuntimeError(f"native build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, _OUT)
    finally:
        tmp.unlink(missing_ok=True)
    return _OUT


if __name__ == "__main__":
    print(ensure_built(force=True))
