"""HuggingFace Llama-family checkpoint -> starway-tpu parameter tree.

Bridges the ecosystem's weights into this framework — six served
families: ``transformers.LlamaForCausalLM``, ``MistralForCausalLM``
(sliding-window attention -> ``LlamaConfig.sliding_window``),
``Qwen2ForCausalLM`` (q/k/v projection biases ->
``cfg.attn_bias``/``bq``/``bk``/``bv`` leaves), ``MixtralForCausalLM``
(SwiGLU top-2 MoE experts -> ``cfg.moe_swiglu``, dropless conversion
capacity), ``GemmaForCausalLM`` (GeGLU -> ``cfg.mlp_act``, the
(1 + w) RMSNorm convention folded into the converted weights,
sqrt(d_model)-scaled embeddings -> ``cfg.scaled_embed``), and
``Phi3ForCausalLM`` (fused ``qkv_proj``/``gate_up_proj`` row-sliced into
separate projections at conversion) — all into the
stacked-layer pytree ``models/llama.py`` trains and serves;
``config_from_hf`` derives the matching :class:`LlamaConfig`, including
modern variants with decoupled ``head_dim`` and linear/llama3
``rope_scaling``.

Convention notes (why this is transpose-and-stack, not surgery):

* HF's ``apply_rotary_pos_emb`` uses the rotate-half (NeoX / split-half)
  convention — the same one ``llama.apply_rope`` implements — so q/k
  projections carry over with NO column permutation.  (Meta's original
  release uses interleaved pairs; HF already permuted at import, and
  loading a Meta-native checkpoint still requires that permutation, as
  documented on ``apply_rope``.)
* HF ``nn.Linear`` stores ``[out, in]``; this tree stores ``[in, out]`` —
  every projection transposes.
* HF models may tie ``lm_head`` to the embedding; the converter follows
  ``get_output_embeddings``/falls back to the tied table.

Numerical parity with ``LlamaForCausalLM`` forward is pinned by
tests/test_hf_convert.py on a tiny random model.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .llama import LlamaConfig


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """LlamaConfig from a ``transformers.LlamaConfig``-shaped object.

    Refuses configs this model family cannot represent — silently dropping
    them would produce a numerically wrong model (the failure mode this
    module exists to prevent)."""
    if getattr(hf_config, "mlp_bias", False):
        raise NotImplementedError(
            "MLP biases are not represented in this parameter tree")
    model_type = getattr(hf_config, "model_type", "")
    if model_type in ("gemma2", "gemma3", "gemma3_text"):
        # Must precede the activation check, or these fall into the
        # generic hidden_act error with a misleading message.
        raise NotImplementedError(
            f"{model_type} adds logit soft-capping and pre/post "
            "feed-forward norms this tree does not represent; gemma (v1) "
            "converts")
    act = (getattr(hf_config, "hidden_activation", None)
           or getattr(hf_config, "hidden_act", "silu"))
    if act in ("silu", "swish"):
        mlp_act = "silu"
    elif act in ("gelu_pytorch_tanh", "gelu_tanh") and model_type == "gemma":
        mlp_act = "gelu_tanh"  # Gemma's GeGLU
    else:
        raise NotImplementedError(
            f"hidden_act={act!r} on model_type={model_type!r}; this family "
            "is gated-MLP with silu (Llama) or gelu_tanh (Gemma)")
    # Qwen2-family checkpoints attach q/k/v biases (cfg.attn_bias ->
    # bq/bk/bv leaves; Qwen2's o_proj carries NO bias, so the tree is
    # complete).  A generic attention_bias=True config is a DIFFERENT
    # shape: HF Llama then puts a bias on o_proj too, which this tree
    # does not represent — refuse rather than silently drop it.
    attn_bias = model_type == "qwen2"
    if getattr(hf_config, "attention_bias", False) and not attn_bias:
        raise NotImplementedError(
            "attention_bias=True on a non-Qwen2 config also biases o_proj, "
            "which this parameter tree does not represent; converting "
            "would silently drop it")
    # Qwen2 gates its sliding_window on use_sliding_window (default
    # False), and even then windows only the layers PAST
    # max_window_layers — a mixed pattern cfg.sliding_window (global)
    # cannot express.  Honour the gate; refuse the mixed case.
    sliding = getattr(hf_config, "sliding_window", None)
    if sliding is not None and hasattr(hf_config, "use_sliding_window"):
        mwl = getattr(hf_config, "max_window_layers", 0) or 0
        if not hf_config.use_sliding_window:
            sliding = None
        elif mwl >= hf_config.num_hidden_layers:
            sliding = None  # "first mwl layers full" covers every layer
        elif mwl > 0:
            raise NotImplementedError(
                f"use_sliding_window with max_window_layers={mwl} windows "
                f"only layers past it; this config represents a single "
                "global sliding_window")
    prf = getattr(hf_config, "partial_rotary_factor", None)
    if prf is not None and float(prf) != 1.0:
        raise NotImplementedError(
            f"partial_rotary_factor={prf} rotates only part of each head; "
            "this tree applies rope to the full head dim")
    # Newer HF configs may pin an explicit per-head dim decoupled from
    # hidden_size // num_attention_heads; llama.py keys every
    # projection/reshape off cfg.head_dim, so the override carries it.
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = (hf_config.hidden_size // hf_config.num_attention_heads
                  if hf_config.hidden_size % hf_config.num_attention_heads == 0
                  else None)
    if explicit_hd is None and derived_hd is None:
        raise NotImplementedError(
            f"hidden_size={hf_config.hidden_size} is not divisible by "
            f"num_attention_heads={hf_config.num_attention_heads} and the "
            "config pins no explicit head_dim")
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        # Mistral-family configs carry sliding_window; same architecture
        # otherwise, so the converter serves both families.
        sliding_window=sliding,
        attn_bias=attn_bias,
        head_dim_override=(explicit_hd if explicit_hd is not None
                           and explicit_hd != derived_hd else None),
        rope_scaling=_rope_scaling_from_hf(
            getattr(hf_config, "rope_scaling", None),
            getattr(hf_config, "max_position_embeddings", None),
            getattr(hf_config, "original_max_position_embeddings", None)),
        mlp_act=mlp_act,
        # Gemma scales the embedding OUTPUT by sqrt(d_model); the tied
        # lm_head reads the raw table, so it is a runtime flag, not a
        # weight fold.
        scaled_embed=model_type == "gemma",
    )
    if model_type == "mixtral":
        # Mixtral: SwiGLU experts, top-k routing with softmax-then-topk
        # renormalisation — exactly moe.py's _route.  HF routes dropless;
        # capacity_factor = n_experts makes our static capacity provably
        # dropless (capacity = T * k) so converted models match
        # transformers token for token.  Lower it for capacity-bound
        # training throughput at the cost of that guarantee.
        kw.update(
            n_experts=hf_config.num_local_experts,
            moe_top_k=hf_config.num_experts_per_tok,
            moe_swiglu=True,
            moe_capacity_factor=float(hf_config.num_local_experts),
            moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef",
                                       0.001)),
        )
    kw.update(overrides)
    return LlamaConfig(**kw)


def _rope_scaling_from_hf(scaling, max_position_embeddings=None,
                          original_max_position_embeddings=None) -> "tuple | None":
    """HF ``rope_scaling`` dict -> LlamaConfig's hashable tuple.

    Implemented kinds: ``linear`` (position interpolation), ``llama3``
    (the Llama-3.1 banded scheme), ``yarn`` (NTK-by-parts,
    Qwen2.5-long / DeepSeek-family), and ``longrope`` (per-dim factor
    lists, Phi-3.5/128k line; see llama.py:rope_tables).  yarn's
    ``attention_factor`` is resolved HERE, HF-identically — explicit
    value wins, then the mscale/mscale_all_dim ratio (DeepSeek), then
    the paper default ``0.1*ln(factor)+1`` — so the config tuple carries
    one final float.  Anything else (dynamic, ...) still refuses —
    silently dropping a scaling scheme would change the rope
    frequencies vs transformers, the exact failure mode this module
    exists to prevent."""
    if not scaling:
        return None
    kind = scaling.get("rope_type", scaling.get("type"))
    if kind == "linear":
        return ("linear", float(scaling["factor"]))
    if kind == "llama3":
        return ("llama3", float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                float(scaling["original_max_position_embeddings"]))
    if kind == "yarn":
        import math

        factor = float(scaling["factor"])
        att = scaling.get("attention_factor")
        mscale = scaling.get("mscale")
        mscale_all_dim = scaling.get("mscale_all_dim")

        def get_mscale(scale, m=1.0):
            return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

        if att is None:
            if mscale and mscale_all_dim:
                att = get_mscale(factor, mscale) / get_mscale(
                    factor, mscale_all_dim)
            else:
                att = get_mscale(factor)
        orig = (scaling.get("original_max_position_embeddings")
                or max_position_embeddings)
        if orig is None:
            raise ValueError(
                "yarn rope_scaling needs original_max_position_embeddings "
                "(in the scaling dict or the model config)")
        return ("yarn", factor, float(orig),
                float(scaling.get("beta_fast") or 32),
                float(scaling.get("beta_slow") or 1),
                float(att), bool(scaling.get("truncate", True)))
    if kind == "longrope":
        import math

        short = tuple(float(x) for x in scaling["short_factor"])
        long = tuple(float(x) for x in scaling["long_factor"])
        # HF: Phi3-style configs carry original_max_position_embeddings
        # at the CONFIG level and derive factor from the max/orig ratio;
        # otherwise the scaling dict's factor applies and orig = max.
        orig = original_max_position_embeddings
        if orig:
            factor = float(max_position_embeddings) / float(orig)
        else:
            orig = max_position_embeddings
            factor = scaling.get("factor")
        if orig is None or factor is None:
            raise ValueError(
                "longrope rope_scaling needs original_max_position_"
                "embeddings (config level) or an explicit factor")
        att = scaling.get("attention_factor")
        if att is None:
            att = (1.0 if factor <= 1.0
                   else math.sqrt(1.0 + math.log(factor) / math.log(orig)))
        # NOTE: the regime (short vs long factors) is chosen per rope
        # TABLE by its seq_len (llama.py:rope_tables).  A generation
        # whose horizon crosses orig uses one regime for the whole run;
        # HF switches per step on such runs and diverges there.
        return ("longrope", float(orig), float(att), short, long)
    if kind == "default":
        # transformers normalises "no scaling" configs to
        # {"rope_type": "default"} in some versions.
        return None
    raise NotImplementedError(
        f"rope_scaling={scaling!r} is not implemented here (linear, "
        "llama3, yarn, and longrope are); converting would silently "
        "change the rope frequencies vs transformers")


def _norm_w(w, plus_one: bool) -> np.ndarray:
    """RMSNorm weight, with Gemma's ``x̂ * (1 + w)`` convention folded to
    ``w' = 1 + w`` so the framework's plain ``x̂ * w`` is exact (the
    addition runs in f32 before the dtype cast, matching HF's f32 norm
    math)."""
    w = _np(w)
    return w + 1.0 if plus_one else w


def _t(w) -> np.ndarray:
    """torch/np tensor -> f32 numpy, transposed ([out, in] -> [in, out])."""
    return _np(w).T


def _np(w) -> np.ndarray:
    if hasattr(w, "detach"):
        w = w.detach().cpu().float().numpy()
    return np.asarray(w, dtype=np.float32)


def params_from_hf(model_or_state: Any, cfg: LlamaConfig, dtype=None, *,
                   quantize: str = "none",
                   norm_plus_one: "bool | None" = None) -> dict:
    """Convert a ``LlamaForCausalLM`` (or its ``state_dict()``) into this
    framework's stacked-layer parameter pytree, cast to ``dtype`` (default:
    ``cfg.compute_dtype``).

    Each leaf is cast and committed to jax AS it is converted, so peak host
    memory is the source checkpoint plus one stacked leaf's f32 scratch —
    not a second full-tree copy.

    ``quantize="int8"``: return the W8A16 serving tree
    (ops/quantize.py:quantize_params applied after conversion) — every
    matmul weight as per-output-channel int8 + scales, half the weight
    HBM, inference-only (see models/llama.py:matmul_w).

    ``norm_plus_one``: Gemma computes RMSNorm as ``x̂ * (1 + w)`` with
    zero-init weights; the fold ``w' = 1 + w`` at conversion makes the
    framework's plain ``x̂ * w`` norm exact with NO runtime flag.
    Defaults to ``cfg.scaled_embed`` (the Gemma marker config_from_hf
    sets), so Gemma state DICTS fold correctly too."""
    import jax.numpy as jnp

    if quantize not in ("none", "int8"):
        # Before the conversion work, not after.
        raise ValueError(f"quantize must be 'none' or 'int8', got {quantize!r}")
    if norm_plus_one is None:
        # cfg.scaled_embed is set by config_from_hf exactly for Gemma —
        # keying off the passed cfg (not model_or_state.config, absent on
        # raw state dicts) keeps dict conversions correct by default.
        norm_plus_one = cfg.scaled_embed
    if hasattr(model_or_state, "state_dict"):
        state = {k: v for k, v in model_or_state.state_dict().items()}
    else:
        state = dict(model_or_state)
    # Accept both bare-LlamaModel ("model.layers...") and ForCausalLM keys.
    prefix = "model." if any(k.startswith("model.") for k in state) else ""

    dt = jnp.dtype(dtype) if dtype is not None else cfg.compute_dtype

    def get(name):
        return state[prefix + name]

    L = cfg.n_layers
    stack = lambda fn: jnp.asarray(np.stack([fn(i) for i in range(L)]), dt)
    fused = prefix + "layers.0.self_attn.qkv_proj.weight" in state
    if fused:
        if (prefix + "layers.0.self_attn.qkv_proj.bias" in state
                or prefix + "layers.0.mlp.gate_up_proj.bias" in state):
            # Same loud-refusal contract as the split-projection bias
            # probes below: silently dropping a bias is a wrong model.
            raise NotImplementedError(
                "fused qkv_proj/gate_up_proj biases are not represented "
                "in this parameter tree; converting would silently drop "
                "them")
        # Phi-3 family: one fused qkv_proj [(Hq + 2*Hkv) * hd, D] — slice
        # the OUT rows (HF [out, in]) into q/k/v before the transpose.
        # Convert each fused tensor to f32 numpy ONCE and slice the cached
        # copy (three fresh .float().numpy() copies per layer would 3x the
        # conversion scratch the module docstring bounds).
        nq = cfg.n_heads * cfg.head_dim
        nkv = cfg.n_kv_heads * cfg.head_dim

        def qkv_split(i):
            w = _np(get(f"layers.{i}.self_attn.qkv_proj.weight"))
            # .copy(): a view would pin the whole fused matrix until the
            # final stack (L of them at once).
            return (w[0:nq].T.copy(), w[nq:nq + nkv].T.copy(),
                    w[nq + nkv:nq + 2 * nkv].T.copy())

        qkv = [qkv_split(i) for i in range(L)]
        wq = jnp.asarray(np.stack([q for q, _, _ in qkv]), dt)
        wk = jnp.asarray(np.stack([k for _, k, _ in qkv]), dt)
        wv = jnp.asarray(np.stack([v for _, _, v in qkv]), dt)
        del qkv
    else:
        wq = stack(lambda i: _t(get(f"layers.{i}.self_attn.q_proj.weight")))
        wk = stack(lambda i: _t(get(f"layers.{i}.self_attn.k_proj.weight")))
        wv = stack(lambda i: _t(get(f"layers.{i}.self_attn.v_proj.weight")))
    layers = {
        "wq": wq,
        "wk": wk,
        "wv": wv,
        "wo": stack(lambda i: _t(get(f"layers.{i}.self_attn.o_proj.weight"))),
        "attn_norm": stack(lambda i: _norm_w(
            get(f"layers.{i}.input_layernorm.weight"), norm_plus_one)),
        "mlp_norm": stack(lambda i: _norm_w(
            get(f"layers.{i}.post_attention_layernorm.weight"),
            norm_plus_one)),
    }
    if prefix + "layers.0.block_sparse_moe.gate.weight" in state:
        # Mixtral: gate -> router [D, E]; per-expert SwiGLU maps
        # w1 -> w_gate, w3 -> w_in, w2 -> w_out (all [out, in] -> [in, out]
        # transposes), stacked to [L, E, ...].
        E = cfg.n_experts

        def estack(which):
            return jnp.asarray(np.stack([
                np.stack([_t(get(f"layers.{i}.block_sparse_moe.experts."
                              f"{e}.{which}.weight")) for e in range(E)])
                for i in range(L)]), dt)

        layers["moe"] = {
            "router": stack(
                lambda i: _t(get(f"layers.{i}.block_sparse_moe.gate.weight"))),
            "w_gate": estack("w1"),
            "w_in": estack("w3"),
            "w_out": estack("w2"),
        }
    elif fused:
        # Phi-3's fused gate_up_proj [2F, D]: first F rows gate, last F up
        # (Phi3MLP chunks dim -1 after the matmul, gate first).  One f32
        # conversion per layer, sliced cached.
        F = cfg.d_ff

        def gu_split(i):
            w = _np(get(f"layers.{i}.mlp.gate_up_proj.weight"))
            return w[:F].T.copy(), w[F:2 * F].T.copy()

        gu = [gu_split(i) for i in range(L)]
        layers.update(
            w_gate=jnp.asarray(np.stack([g for g, _ in gu]), dt),
            w_up=jnp.asarray(np.stack([u for _, u in gu]), dt),
            w_down=stack(
                lambda i: _t(get(f"layers.{i}.mlp.down_proj.weight"))),
        )
        del gu
    else:
        layers.update(
            w_gate=stack(lambda i: _t(get(f"layers.{i}.mlp.gate_proj.weight"))),
            w_up=stack(lambda i: _t(get(f"layers.{i}.mlp.up_proj.weight"))),
            w_down=stack(
                lambda i: _t(get(f"layers.{i}.mlp.down_proj.weight"))),
        )
    if prefix + "layers.0.self_attn.o_proj.bias" in state:
        # config_from_hf refuses these configs; a raw state dict can still
        # reach here — same refusal, same reason.
        raise NotImplementedError(
            "o_proj carries a bias, which this parameter tree does not "
            "represent; converting would silently drop it")
    if prefix + "layers.0.self_attn.q_proj.bias" in state:
        # Qwen2 family: per-head projection biases (qkv_proj keys off the
        # leaves' presence; HF bias vectors are [out] — no transpose).
        layers.update(
            bq=stack(lambda i: _np(get(f"layers.{i}.self_attn.q_proj.bias"))),
            bk=stack(lambda i: _np(get(f"layers.{i}.self_attn.k_proj.bias"))),
            bv=stack(lambda i: _np(get(f"layers.{i}.self_attn.v_proj.bias"))),
        )
    embed = jnp.asarray(_np(get("embed_tokens.weight")), dt)
    if "lm_head.weight" in state:
        lm_head = jnp.asarray(_t(state["lm_head.weight"]), dt)
    else:  # tied embeddings
        lm_head = embed.T
    params = {
        "embed": embed,
        "layers": layers,
        "final_norm": jnp.asarray(
            _norm_w(get("norm.weight"), norm_plus_one), dt),
        "lm_head": lm_head,
    }
    if quantize == "int8":
        from ..ops.quantize import quantize_params

        return quantize_params(params)
    return params
