"""Minimal training harness for the model family.

Wires the pieces the framework already provides into one loop: the jitted
(optionally sharded) train step, telemetry (utils.OpTimer), checkpointing
(utils.checkpoint), and -- when a DP-boundary port is supplied -- averaged
gradient exchange with a peer host over the async P2P fabric
(parallel/dp_exchange.py; the examples/dp_training_2proc.py pattern as a
library).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax

from ..utils import OpTimer
from .llama import LlamaConfig, apply_updates, loss_fn, make_train_step


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


class Trainer:
    def __init__(self, cfg: LlamaConfig, tx, params,
                 attn_fn: Optional[Callable] = None,
                 donate: bool = True,
                 dp_port=None, dp_base_tag: int = 0x6000,
                 mesh=None, fsdp_axis: Optional[str] = None,
                 moe_fn: Optional[Callable] = None,
                 with_moe_stats: bool = False,
                 accum_steps: int = 1):
        """``dp_port``: a ClientPort/ServerPort to a peer rank; when set,
        gradients are averaged with the peer every step before the update.

        ``moe_fn``: MoE dispatch override for expert models (e.g.
        :func:`~starway_tpu.models.moe.make_sharded_moe`'s result).
        ``with_moe_stats`` (needs a ``with_stats=True`` moe_fn): every step
        stashes the layer-stacked router-health dict (drop fraction,
        per-expert load) on ``self.last_moe_stats`` — the training loop
        watches a collapsing router without changing ``step_sync``'s
        return type.

        ``dp_base_tag``: start of the tag range the exchange occupies.  The
        rolling window spans ``[dp_base_tag, dp_base_tag + 1024*256)`` —
        1024 in-flight steps x 256 leaves — so any *other* pytree exchange
        sharing this worker must use tags outside that 0x40000-wide range.

        ``mesh`` + ``fsdp_axis``: ZeRO mode — params and optimizer state are
        sharded 1/N over that mesh axis (parallel/fsdp.py) and ``step_sync``
        runs the fused sharded train step (batch sharded over the same
        axis).  Mutually exclusive with ``dp_port``: the P2P gradient
        exchange assumes host-visible unsharded grads.

        ``accum_steps``: gradient accumulation — the batch splits into
        that many equal microbatches whose f32-accumulated grads feed ONE
        optimizer update (make_train_step's semantics: activation memory
        scales with the microbatch, the math matches the full-batch step
        for dense models).  Local/fsdp step only; the dp_port exchange
        path averages full-batch grads and stays accum_steps=1.
        """
        self.cfg = cfg
        self.tx = tx
        self.state = TrainState(params=params, opt_state=tx.init(params))
        self.timer = OpTimer()
        self.dp_port = dp_port
        self.dp_base_tag = dp_base_tag
        self.with_moe_stats = with_moe_stats
        self.last_moe_stats = None
        self._fsdp_step = None
        if with_moe_stats and mesh is not None:
            raise NotImplementedError(
                "with_moe_stats is not wired through the fused fsdp step; "
                "use the plain step or make_train_step(with_moe_stats=True)")
        if with_moe_stats and (moe_fn is None or cfg.n_experts == 0):
            # Fail at construction, not at the first step inside tracing.
            raise ValueError(
                "with_moe_stats needs an expert config and a stats-producing"
                " moe_fn (make_sharded_moe(..., with_stats=True))")
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        if accum_steps > 1 and dp_port is not None:
            raise ValueError(
                "accum_steps composes with the local/fsdp step only; the "
                "dp_port exchange path averages full-batch grads")
        if (mesh is None) != (fsdp_axis is None):
            raise ValueError("pass mesh and fsdp_axis together")
        if mesh is not None:
            if dp_port is not None:
                raise ValueError("fsdp mode and dp_port are mutually exclusive")
            from ..parallel.fsdp import (fsdp_specs, make_fsdp_train_step,
                                         shard_tree)

            pspecs = fsdp_specs(params, mesh, axis=fsdp_axis)
            ospecs = fsdp_specs(jax.eval_shape(tx.init, params), mesh,
                                axis=fsdp_axis)
            self.state.params = shard_tree(self.state.params, mesh, pspecs)
            self.state.opt_state = shard_tree(self.state.opt_state, mesh, ospecs)
            self._fsdp_step = make_fsdp_train_step(
                make_train_step(cfg, tx, attn_fn, moe_fn,
                                accum_steps=accum_steps), mesh, pspecs,
                ospecs, axis=fsdp_axis, donate=donate)
        if dp_port is not None:
            # step_dp gives each step a 256-tag window (base advances by 256
            # per step); more leaves than that would collide across steps.
            n_leaves = len(jax.tree_util.tree_leaves(params))
            if n_leaves > 256:
                raise ValueError(
                    f"DP gradient exchange supports <= 256 pytree leaves per "
                    f"step; got {n_leaves} (stack per-layer params, or widen "
                    f"the tag window)"
                )
        self._grad_fn = jax.jit(
            lambda p, b: jax.value_and_grad(loss_fn, has_aux=with_moe_stats)(
                p, b, cfg, attn_fn, moe_fn, with_moe_stats=with_moe_stats))
        self._apply_fn = jax.jit(
            lambda p, o, g: apply_updates(tx, p, o, g),
            donate_argnums=(0, 1) if donate else (),
        )
        self._accum_step = None
        if accum_steps > 1 and self._fsdp_step is None:
            # The fused accumulate-then-update step (make_train_step's
            # lax.scan over microbatches); step_sync dispatches to it.
            self._accum_step = jax.jit(
                make_train_step(cfg, tx, attn_fn, moe_fn,
                                accum_steps=accum_steps,
                                with_moe_stats=with_moe_stats),
                donate_argnums=(0, 1) if donate else ())

    def step_sync(self, batch) -> float:
        """One local step (no DP exchange)."""
        if self._accum_step is not None:
            with self.timer.span("accum_step"):
                out = self._accum_step(self.state.params,
                                       self.state.opt_state, batch)
                if self.with_moe_stats:
                    (self.state.params, self.state.opt_state, loss,
                     self.last_moe_stats) = out
                else:
                    self.state.params, self.state.opt_state, loss = out
            self.state.step += 1
            return float(loss)
        if self._fsdp_step is not None:
            with self.timer.span("fsdp_step"):
                self.state.params, self.state.opt_state, loss = self._fsdp_step(
                    self.state.params, self.state.opt_state, batch)
            self.state.step += 1
            return float(loss)
        with self.timer.span("grad"):
            loss, grads = self._unpack_grad(
                self._grad_fn(self.state.params, batch))
        with self.timer.span("apply"):
            self.state.params, self.state.opt_state = self._apply_fn(
                self.state.params, self.state.opt_state, grads
            )
        self.state.step += 1
        return float(loss)

    def _unpack_grad(self, out):
        """(loss[, stats]), grads -> (loss, grads); stats stashed."""
        val, grads = out
        if self.with_moe_stats:
            loss, self.last_moe_stats = val
            return loss, grads
        return val, grads

    async def step_dp(self, batch) -> float:
        """One step with averaged gradient exchange across the DP port."""
        import asyncio

        from ..parallel.dp_exchange import recv_pytree, send_pytree

        with self.timer.span("grad"):
            loss, grads = self._unpack_grad(
                self._grad_fn(self.state.params, batch))
        with self.timer.span("dp_exchange"):
            base = self.dp_base_tag + (self.state.step % 1024) * 256
            send_task = asyncio.ensure_future(
                send_pytree(self.dp_port, grads, base_tag=base)
            )
            peer = await recv_pytree(self.dp_port, like=grads, base_tag=base)
            await send_task
            grads = jax.tree_util.tree_map(lambda a, b: (a + b) / 2, grads, peer)
        with self.timer.span("apply"):
            self.state.params, self.state.opt_state = self._apply_fn(
                self.state.params, self.state.opt_state, grads
            )
        self.state.step += 1
        return float(loss)

    # ------------------------------------------------------------ ckpt
    def save(self, path: str) -> str:
        from ..utils.checkpoint import save_pytree

        return save_pytree(path, {"params": self.state.params,
                                  "opt_state": self.state.opt_state,
                                  "step": jax.numpy.asarray(self.state.step)})

    def restore(self, path: str) -> None:
        from ..utils.checkpoint import restore_pytree

        like = {"params": self.state.params, "opt_state": self.state.opt_state,
                "step": jax.numpy.asarray(self.state.step)}
        got = restore_pytree(path, like)
        self.state = TrainState(params=got["params"], opt_state=got["opt_state"],
                                step=int(got["step"]))

    def telemetry(self) -> dict:
        return self.timer.summary()
