"""Speculative decoding: draft-model proposal + single-dispatch chunk verify.

Decode is HBM-bandwidth-bound — every generated token streams the whole KV
cache once (BASELINE.md decode rows).  Speculative decoding (Leviathan et
al. 2023 / Chen et al. 2023, public algorithm) breaks the one-token-per-
stream limit: a cheap DRAFT model proposes ``gamma - 1`` tokens
autoregressively, then the TARGET model scores the whole proposed chunk in
ONE forward pass — the target's cache streams once per ``a + 1`` accepted
tokens instead of once per token, and the rejection rule keeps the output
distribution EXACTLY the target model's (greedy case: identical tokens up
to bf16 argmax near-ties between the chunk and stepwise forwards — the two
compute the same logits through different summation orders; pinned exactly
on the CPU mesh by tests/test_speculative.py, and the chunk-vs-stepwise
logit gap is pinned on hardware by ``kernel_bench --kernels check``'s
``check_spec_chunk_onchip`` row).

TPU-first construction, mirroring models/generate.py's discipline:

* the whole generation is one ``lax.while_loop`` dispatch — draft scan,
  chunk verify, acceptance, and output writes are all on-device (a host
  round trip per macro step would cost ~100 ms behind this sandbox's
  tunnel against a few-ms verify);
* static shapes throughout: every macro step drafts exactly ``gamma - 1``
  tokens and verifies a ``gamma`` chunk; per-row cursors absorb the
  variable acceptance length (rows advance 1..gamma tokens per step);
* cache rollback is FREE: rejected positions sit beyond the row's cursor,
  where position masking hides them and later writes overwrite them — no
  copy, no checkpoint (the same invariant ragged decode relies on).

No reference counterpart (/root/reference is a transport library); this is
the TPU build's serving-stack extension implementing the public algorithm.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .generate import _filter_logits, _sample, cached_layer_scan, prefill
from .llama import (LlamaConfig, cfg_rope_tables, embed_tokens, matmul_w,
                    rmsnorm)


def chunk_decode_step(params, cache, tokens, pos, cfg: LlamaConfig, rope):
    """``C`` tokens in, ``C`` next-token logits out — the multi-token
    generalisation of :func:`~starway_tpu.models.generate.decode_step`
    (C=1 reduces to it, pinned by tests).

    tokens: [B, C] int32 at ABSOLUTE positions ``pos .. pos + C - 1``
    (``pos`` scalar or per-row [B]).  Returns ``(logits [B, C, V] f32,
    updated cache)``.  Write-then-attend: the chunk's k/v (quantized when
    the cache is int8) land in the cache first, then the chunk attends
    through it with per-row global-position masks — in-chunk causality
    falls out of the positions.  This is the speculative VERIFY step, and
    generally useful for multi-token ingestion (teacher forcing, cache
    warm-up) at decode-path semantics.  Dense FFN and MoE follow
    decode_step; rolling caches are not supported (speculative decoding
    targets the full-cache path) — a window-sized cache raises rather
    than silently writing absolute positions into a modular window.  The
    check is a shape heuristic (rolling and full caches share a layout),
    so a FULL cache allocated with max_len exactly == sliding_window is
    rejected too; allocate max_len = window + C for ingestion — positions
    past the window are masked out of attention anyway, so the extra
    slots change nothing.
    """
    B, C = tokens.shape
    T_cache = cache["k"].shape[3]
    if cfg.sliding_window is not None and T_cache == cfg.sliding_window:
        # Mirrors decode_step's rolling-cache shape check, inverted: a
        # cache of exactly sliding_window slots is a rolling cache
        # (init_rolling_cache), whose modular slots this absolute-position
        # write-then-attend cannot address — dynamic_update_slice would
        # clamp the write and the masks would lie.
        raise ValueError(
            f"chunk_decode_step does not support rolling caches: got a "
            f"{T_cache}-slot cache == cfg.sliding_window, which is "
            f"init_rolling_cache's layout; allocate a full cache "
            f"(init_cache with max_len != sliding_window — positions past "
            f"the window are masked anyway, so max_len = window + C costs "
            f"nothing) for chunk verify / multi-token ingestion")
    n_rep = cfg.n_heads // cfg.n_kv_heads
    cos, sin = rope
    pos = jnp.asarray(pos, jnp.int32)
    pos_b = pos if pos.ndim == 1 else jnp.broadcast_to(pos, (B,))
    pos_bc = pos_b[:, None] + jnp.arange(C)[None, :]  # [B, C]
    cos_p = cos[pos_bc][:, None]  # [B, 1, C, hd/2]
    sin_p = sin[pos_bc][:, None]

    def write(c, u):
        """C contiguous entries at each row's cursor; same per-leaf axis
        invariant as decode_step (T axis at index 1 per row)."""
        return jax.vmap(
            lambda cr, ur, p: lax.dynamic_update_slice_in_dim(
                cr, ur, p, axis=1))(c, u, pos_b)

    def attend(q, layer_cache):
        # The SAME grouped-stream attention decode_step uses, at C query
        # positions: on TPU the pallas kernel packs C x n_rep rows into
        # one per-(batch, kv head) matmul over the narrow (int8-capable)
        # cache stream — the verify costs one decode step's bytes.
        from .generate import _attend_cached

        return _attend_cached(q, layer_cache["k"], layer_cache["v"], pos_b,
                              n_rep, window=cfg.sliding_window,
                              k_scale=layer_cache.get("k_scale"),
                              v_scale=layer_cache.get("v_scale"))

    h = embed_tokens(params, tokens, cfg)  # [B, C, D]
    h, out = cached_layer_scan(params, cache, h, cos_p, sin_p, cfg, write,
                               attend)
    h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = matmul_w(h, params["lm_head"]).astype(jnp.float32)  # [B, C, V]
    return logits, out


# ------------------------------------------------------------- the driver


def _accept_emit(drafts, pd, t_logits, key, out, n_out, t_pend, pos, stats,
                 *, greedy: bool, G: int, B: int, max_new: int, probs_of):
    """The acceptance rule + output bookkeeping every speculative driver
    shares (model-draft and prompt-lookup): leading-accept count, the
    correction/bonus token, per-row emit at the cursor, and the
    freeze/clamp logic that keeps every position inside max_len.

    drafts [B, G-1], pd [B, G-1, V] (the PROPOSAL distributions — one-hot
    for deterministic drafters), t_logits [B, G, V] from the chunk
    verify.  Returns ``(out, n_out, t_pend, pos, key, stats, emit)``;
    ``emit [B, G]`` is the written token vector ([d_1..d_a, c, junk]) so
    a caller maintaining its own sequence buffer can mirror the write.
    """
    idx = jnp.arange(G - 1)[None, :]
    if greedy:
        tgt = jnp.argmax(t_logits[:, :-1], -1)  # [B, G-1]
        ok = drafts == tgt
    else:
        qt = probs_of(t_logits[:, :-1])  # [B, G-1, V]
        key, akey = jax.random.split(key)
        u = jax.random.uniform(akey, drafts.shape)
        take = jnp.take_along_axis
        qt_d = take(qt, drafts[..., None], -1)[..., 0]
        pd_d = take(pd, drafts[..., None], -1)[..., 0]
        # STRICT inequality: u == 0 with qt_d == 0 (draft proposed
        # outside the target's top-k/top-p support) must reject —
        # plain generate() can never emit that token.
        ok = u * pd_d < qt_d
    # a = leading-accept count in [0, G-1].
    a = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)

    # The correction/bonus token at pos + a + 1.
    la = jnp.take_along_axis(t_logits, a[:, None, None], axis=1)[:, 0]
    key, ckey = jax.random.split(key)
    if greedy:
        # Rejected d was != argmax, so the correction IS argmax; full
        # acceptance's bonus is argmax of the last logits.
        c = jnp.argmax(la, -1).astype(jnp.int32)
    else:
        qa = probs_of(la)
        # Residual only where a rejection happened (a < G-1); full
        # acceptance samples the bonus from q_T directly.
        pa = jnp.take_along_axis(
            jnp.pad(pd, ((0, 0), (0, 1), (0, 0))),
            a[:, None, None], axis=1)[:, 0]
        res = jnp.maximum(qa - pa, 0.0)
        res_sum = jnp.sum(res, -1, keepdims=True)
        # Degenerate residual (q_T <= p_D everywhere it was sampled-able
        # can leave ~0 mass after float error): fall back to q_T.
        use_res = (a[:, None] < G - 1) & (res_sum > 1e-9)
        dist = jnp.where(use_res, res / jnp.maximum(res_sum, 1e-30), qa)
        c = jax.random.categorical(
            ckey, jnp.log(jnp.maximum(dist, 1e-30)), axis=-1
        ).astype(jnp.int32)

    # Emit d_1..d_a then c: a+1 tokens at each row's cursor.
    emit = jnp.where(idx < a[:, None], drafts, 0)
    emit = jnp.concatenate([emit, jnp.zeros((B, 1), jnp.int32)], 1)
    emit = emit.at[jnp.arange(B), a].set(c)  # [B, G]
    out = jax.vmap(
        lambda row, w, s: lax.dynamic_update_slice(row, w, (s,))
    )(out, emit, n_out)
    # Finished rows freeze (cursor, position, pending token): they keep
    # re-running the same macro step while slower rows catch up.  The
    # advance is CLAMPED to the remaining budget so the invariant
    # pos == P + n_out - 1 holds exactly — pos never exceeds
    # P + max_new - 1, keeping every rope gather and cache write
    # (<= pos + G - 1) inside max_len even on the finishing step; a
    # clamped row keeps its stale pending token, which is never read
    # into the returned slice.
    done = n_out >= max_new
    adv = jnp.where(done, 0, jnp.minimum(a + 1, max_new - n_out))
    n_out = n_out + adv
    live = (~done).astype(jnp.int32)
    # ``accepted`` counts accepted draft tokens actually EMITTED: normally
    # ``a`` (adv = a + 1), but a finishing row clamps its advance, and the
    # budget-truncated write is all drafts (the correction never lands) —
    # min(a, adv) — so accepted + macro_steps never exceeds emitted tokens.
    stats = stats + jnp.stack([live, live * jnp.minimum(a, adv)], axis=1)
    return (out, n_out, jnp.where(adv == a + 1, c, t_pend), pos + adv, key,
            stats, emit)


def draft_from_truncation(params: dict, cfg: LlamaConfig, n_layers: int):
    """A FREE draft model: the target's first ``n_layers`` decoder layers
    with the same embedding, final norm, and head — no second checkpoint,
    no training.  The stacked-layer parameter tree makes this a slice:
    every ``layers`` leaf leads with the layer axis.

    Truncated ("early-exit") drafts are a standard speculative-decoding
    baseline: early layers already predict easy tokens, and easy tokens
    are where acceptance pays.  Returns ``(draft_params, draft_cfg)``
    ready for :func:`generate_speculative`.  Memory: the non-layer leaves
    (embed, final norm, head) are SHARED with the target; the sliced
    ``layers`` leaves are materialised by jax at call time (~n_layers /
    cfg.n_layers of the stacked weights) — budget for that extra HBM on
    a tightly packed chip.
    """
    if not 1 <= n_layers < cfg.n_layers:
        raise ValueError(
            f"n_layers must be in [1, {cfg.n_layers - 1}], got {n_layers}")
    draft_params = dict(params)
    draft_params["layers"] = jax.tree_util.tree_map(
        lambda a: a[:n_layers], params["layers"])
    return draft_params, dataclasses.replace(cfg, n_layers=n_layers)


def _lookup_propose(seq, pos, *, ngram: int, gamma: int):
    """Prompt-lookup proposal: continue the most recent earlier occurrence
    of the sequence's current ``ngram``-gram.

    seq: [B, L] token buffer, valid through index ``pos`` (per-row [B]);
    the current n-gram is ``seq[pos-ngram+1 .. pos]``.  Finds the largest
    j < pos with ``seq[j-ngram+1 .. j]`` equal to it and proposes
    ``seq[j+1 .. j+gamma-1]``.  No match: j falls back to ``ngram - 1``
    (a harmless in-bounds span — the verify rejects bad proposals, it
    never needs them to be good).  Returns ``[B, gamma-1]`` int32.

    Pure gather/compare ops — no model, no host: the drafter is free, so
    any acceptance at all is profit (repetitive text — code, extraction,
    summarisation — accepts a lot; the public "prompt lookup decoding"
    trick used by mainstream serving engines).
    """
    B, L = seq.shape
    idx = jnp.arange(L)[None, :]
    match = jnp.ones((B, L), bool)
    for k in range(ngram):
        # seq[j - k] == seq[pos - k], masked where j - k < 0.  The key
        # gather clamps at 0: when pos < ngram the n-gram does not exist
        # and any (verified-anyway) proposal is acceptable.
        shifted = jnp.pad(seq, ((0, 0), (k, 0)))[:, :L]
        want = jnp.take_along_axis(
            seq, jnp.maximum(pos[:, None] - k, 0), axis=1)
        match = match & (shifted == want) & (idx >= k)
    match = match & (idx < pos[:, None]) & (idx >= ngram - 1)
    j = jnp.max(jnp.where(match, idx, ngram - 1), axis=1)  # [B]
    return jax.vmap(
        lambda row, s: lax.dynamic_slice(row, (s + 1,), (gamma - 1,))
    )(seq, j)


@functools.cache
def _compiled_lookup(cfg: LlamaConfig, B: int, P: int, max_new: int,
                     max_len: int, gamma: int, ngram: int,
                     temperature: float, top_k: Optional[int],
                     top_p: Optional[float], ragged: bool = False):
    """jit'd prompt-lookup speculative generation: the model-draft driver
    with the draft scan replaced by :func:`_lookup_propose` over a
    sequence buffer — ONE model (the target) runs at all, so every
    accepted token saves a whole decode step."""
    rope = cfg_rope_tables(cfg, max_len)
    greedy = temperature == 0.0
    G = gamma

    def probs_of(logits):
        return jax.nn.softmax(_filter_logits(logits, temperature, top_k,
                                             top_p), axis=-1)

    def run(params, prompt, key, lengths):
        lp = (lengths - 1) if ragged else None
        t_logits, t_cache = prefill(params, cfg, prompt, max_len,
                                    logit_positions=lp)
        key, sub = jax.random.split(key)
        t0 = _sample(t_logits, sub, temperature, top_k, top_p)
        pos0 = lengths if ragged else jnp.full((B,), P, jnp.int32)

        # Sequence buffer: prompt, then every emitted token at its
        # absolute position (the lookup corpus grows as generation runs).
        # Ragged rows carry right-pad junk at lengths..P-1, but matching
        # only scans j < pos and emits overwrite from lengths upward, so
        # junk is never a lookup key or a copied span before it is
        # replaced.
        seq = jnp.zeros((B, max_len), jnp.int32)
        seq = lax.dynamic_update_slice(seq, prompt, (0, 0))
        seq = seq.at[jnp.arange(B), pos0].set(t0)

        out = jnp.zeros((B, max_new + G), jnp.int32)
        out = out.at[:, 0].set(t0)
        n_out = jnp.ones((B,), jnp.int32)
        stats0 = jnp.zeros((B, 2), jnp.int32)

        def macro(carry):
            t_cache, seq, out, n_out, t_pend, pos, key, stats = carry
            old_pos = pos

            drafts = _lookup_propose(seq, pos, ngram=ngram, gamma=G)
            pd = jax.nn.one_hot(drafts, cfg.vocab_size, dtype=jnp.float32)

            chunk = jnp.concatenate([t_pend[:, None], drafts], axis=1)
            t_logits, t_cache = chunk_decode_step(params, t_cache, chunk,
                                                  pos, cfg, rope)

            out, n_out, t_pend, pos, key, stats, emit = _accept_emit(
                drafts, pd, t_logits, key, out, n_out, t_pend, pos, stats,
                greedy=greedy, G=G, B=B, max_new=max_new,
                probs_of=probs_of)
            # Mirror the emit into the lookup corpus at the PRE-advance
            # position + 1 (emit holds [d_1..d_a, c, junk]; junk gets
            # overwritten by the next mirror — the same covering argument
            # as the caches).
            seq = jax.vmap(
                lambda row, w, s: lax.dynamic_update_slice(row, w, (s,))
            )(seq, emit, old_pos + 1)
            return (t_cache, seq, out, n_out, t_pend, pos, key, stats)

        def cond(carry):
            return jnp.any(carry[3] < max_new)

        carry = (t_cache, seq, out, n_out, t0, pos0, key, stats0)
        _, _, out, _, _, _, _, stats = lax.while_loop(cond, macro, carry)
        return out[:, :max_new], stats

    return jax.jit(run)


@functools.cache
def _compiled_speculative(cfg: LlamaConfig, draft_cfg: LlamaConfig, B: int,
                          P: int, max_new: int, max_len: int, gamma: int,
                          temperature: float, top_k: Optional[int],
                          top_p: Optional[float], ragged: bool = False):
    """jit'd speculative generation for one (shape, sampling) signature.

    One dispatch: target+draft prefill, then a ``lax.while_loop`` of macro
    steps — draft scan (``gamma - 1`` proposals), one ``gamma``-wide
    target chunk verify, the acceptance rule, per-row output writes.
    Rows advance 1..gamma tokens per macro step behind per-row cursors;
    the loop runs until every row has ``max_new`` tokens (bounded by
    ``max_new`` iterations: every step advances every row by >= 1).
    """
    from .generate import decode_step

    rope = cfg_rope_tables(cfg, max_len)
    greedy = temperature == 0.0
    G = gamma

    def probs_of(logits):
        """The SAME distribution _sample draws from, as probabilities."""
        return jax.nn.softmax(_filter_logits(logits, temperature, top_k,
                                             top_p), axis=-1)

    def run(params, draft_params, prompt, key, lengths):
        # Ragged: right-padded prompts, per-row cursors from the start
        # (the per-row position plumbing is the same machinery the
        # variable-acceptance advance uses anyway).
        lp = (lengths - 1) if ragged else None
        t_logits, t_cache = prefill(params, cfg, prompt, max_len,
                                    logit_positions=lp)
        _, d_cache = prefill(draft_params, draft_cfg, prompt, max_len,
                             logit_positions=lp)

        key, sub = jax.random.split(key)
        t0 = _sample(t_logits, sub, temperature, top_k, top_p)  # [B]

        out = jnp.zeros((B, max_new + G), jnp.int32)
        out = out.at[:, 0].set(t0)
        n_out = jnp.ones((B,), jnp.int32)
        pos0 = lengths if ragged else jnp.full((B,), P, jnp.int32)
        stats0 = jnp.zeros((B, 2), jnp.int32)  # [macro steps, accepted]

        def macro(carry):
            t_cache, d_cache, out, n_out, t_pend, pos, key, stats = carry

            # --- draft phase: G-1 proposals from the draft's own cache.
            # The scan feeds ALL G chunk tokens (t, d_1 .. d_{G-1}) — the
            # last step produces no proposal, it only writes d_{G-1}'s kv,
            # so after a FULL acceptance the draft cache has no hole at
            # pos+G-1 when the next macro step decodes past it (a zero
            # entry there would poison every later proposal).
            def draft_step(dcache, tok, p, k):
                logits, dcache = decode_step(draft_params, dcache, tok, p,
                                             draft_cfg, rope)
                if greedy:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    pd = jax.nn.one_hot(nxt, logits.shape[-1],
                                        dtype=jnp.float32)
                else:
                    nxt = _sample(logits, k, temperature, top_k, top_p)
                    pd = probs_of(logits)
                return dcache, nxt, pd

            def draft_scan(dcache, t_pend, pos, key):
                toks, pds = [], []
                tok = t_pend
                for i in range(G - 1):
                    key, sub = jax.random.split(key)
                    dcache, tok, pd = draft_step(dcache, tok, pos + i, sub)
                    toks.append(tok)
                    pds.append(pd)
                # Cache-write-only step for the last proposal's kv.
                _, dcache = decode_step(draft_params, dcache, tok,
                                        pos + G - 1, draft_cfg, rope)
                return dcache, jnp.stack(toks, 1), jnp.stack(pds, 1)

            key, dkey = jax.random.split(key)
            d_cache, drafts, pd = draft_scan(d_cache, t_pend, pos, dkey)
            # drafts: [B, G-1] proposals d_1..d_{G-1}; pd their proposal
            # distributions [B, G-1, V].

            # --- verify: ONE target forward over [t, d_1..d_{G-1}].
            chunk = jnp.concatenate([t_pend[:, None], drafts], axis=1)
            t_logits, t_cache = chunk_decode_step(params, t_cache, chunk,
                                                  pos, cfg, rope)
            # t_logits[:, i] = p_T(x at pos+i+1 | ..., chunk[:i+1]).

            out, n_out, t_pend, pos, key, stats, _emit = _accept_emit(
                drafts, pd, t_logits, key, out, n_out, t_pend, pos, stats,
                greedy=greedy, G=G, B=B, max_new=max_new,
                probs_of=probs_of)
            return (t_cache, d_cache, out, n_out, t_pend, pos, key, stats)

        def cond(carry):
            return jnp.any(carry[3] < max_new)

        carry = (t_cache, d_cache, out, n_out, t0, pos0, key, stats0)
        _, _, out, _, _, _, _, stats = lax.while_loop(cond, macro, carry)
        return out[:, :max_new], stats

    return jax.jit(run)


def generate_speculative(params: dict, cfg: LlamaConfig, draft_params: dict,
                         draft_cfg: LlamaConfig, prompt,
                         max_new_tokens: int, *, gamma: int = 4,
                         temperature: float = 0.0,
                         key: Optional[jax.Array] = None,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         eos_id: Optional[int] = None,
                         prompt_lengths=None,
                         return_stats: bool = False):
    """Speculative generation: the TARGET model's output at a fraction of
    its decode steps.  prompt: [B, P] int32; returns ``[B, P +
    max_new_tokens]`` (prompt + continuation), the aligned
    :func:`~starway_tpu.models.generate.generate` contract.

    ``gamma``: macro-step width — the draft proposes ``gamma - 1`` tokens
    and the target verifies them (plus samples one more) in ONE forward.
    Per macro step a row advances ``a + 1`` tokens where ``a`` is its
    leading-accept count, so the target streams its cache once per
    ``a + 1`` tokens instead of once per token — the speedup is the
    draft's acceptance rate times that amortisation, minus the draft's
    own cost.

    Greedy (``temperature == 0``) output matches
    ``generate(params, cfg, ...)`` token for token up to bf16 argmax
    near-ties: the chunk verify and the stepwise decode compute the same
    logits through different summation orders, so a near-tied argmax can
    resolve differently in low precision (exact-match pinned on the CPU
    mesh by tests/test_speculative.py; the chunk-vs-stepwise logit gap
    on-chip by kernel_bench's ``check_spec_chunk_onchip``).  The draft
    only changes how fast
    tokens appear.  Sampling uses the standard speculative
    rejection rule against exactly the filtered distribution ``generate``
    samples from, so the per-token output distribution is the target
    model's (statistically pinned).  ``eos_id``: conventional eos-fill,
    applied to the finished buffer.

    ``return_stats``: additionally return an acceptance-health dict (the
    serving analogue of the MoE router stats): per-row ``macro_steps``
    and ``accepted`` counts — their ratio is the realised mean accept
    length ``a``, making the amortisation ``a + 1`` visible so a cold
    draft is distinguishable from a working one without timings.

    ``prompt_lengths`` ([B] ints, RIGHT-padded prompt): ragged batches —
    every row speculates from its own cursor; returns only the NEW
    tokens ``[B, max_new_tokens]`` (the ragged ``generate`` contract).

    Requirements: same vocab on both models; dense FFNs or
    provably-dropless MoE (``moe_capacity_factor >= n_experts``, the
    Mixtral conversion default — shape-invariant routing makes the chunk
    verify route exactly like stepwise decode; droppy capacities
    refuse).  Sliding-window models speculate through FULL caches with
    window masking (the O(window) rolling layout is the one thing not
    wired).
    """
    B, P = prompt.shape
    _validate_spec_args(max_new_tokens, gamma, (cfg, "target"),
                        (draft_cfg, "draft"))
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"target and draft must share a vocab: {cfg.vocab_size} != "
            f"{draft_cfg.vocab_size}")
    lengths = _validate_lengths(prompt_lengths, B, P)
    if key is None:
        key = jax.random.PRNGKey(0)
    # LongRoPE regime resolves at the LOGICAL horizon (prompt + budget),
    # BEFORE the gamma scratch headroom below — spec decode's contract is
    # output-equivalence with generate() at the same request, and
    # generate() resolves at this horizon (llama.resolve_longrope).
    from .llama import resolve_longrope

    cfg = resolve_longrope(cfg, P + max_new_tokens)
    draft_cfg = resolve_longrope(draft_cfg, P + max_new_tokens)
    # Cache headroom: a macro step may write up to gamma - 1 positions
    # past the last kept token before the row's budget check stops it.
    max_len = P + max_new_tokens + gamma
    if max_len == cfg.sliding_window:
        # Dodge chunk_decode_step's rolling-cache shape heuristic (a FULL
        # cache of exactly window slots is indistinguishable from the
        # rolling layout); the extra slot is masked out of attention.
        max_len += 1
    run = _compiled_speculative(cfg, draft_cfg, B, P, max_new_tokens,
                                max_len, int(gamma), float(temperature),
                                top_k, top_p,
                                ragged=prompt_lengths is not None)
    toks, stats = run(params, draft_params, prompt, key, lengths)
    return _finish_spec(prompt, toks, stats, eos_id, return_stats,
                        ragged=prompt_lengths is not None)


def _validate_spec_args(max_new_tokens: int, gamma: int, *cfgs):
    """The restrictions both speculative entry points share."""
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if gamma < 2:
        raise ValueError(f"gamma must be >= 2 (got {gamma}); gamma=1 is "
                         f"plain decode — use generate()")
    from .moe import require_dropless

    for c, who in cfgs:
        if who == "target":
            # Only the TARGET's routing must be shape-invariant (the
            # chunk verify vs stepwise decode); a droppy DRAFT merely
            # proposes worse — the rejection rule keeps the output the
            # target's regardless of how the draft routes.
            require_dropless(c, f"speculative decoding ({who})")
        # Sliding-window configs run fine: the drivers allocate FULL
        # caches (max_len = P + max_new + gamma) and both the draft's
        # decode_step and the chunk verify mask by cfg.sliding_window —
        # only the O(window) ROLLING cache layout is unsupported, and
        # these entry points never allocate one.


def _validate_lengths(prompt_lengths, B: int, P: int):
    """generate()'s ragged-lengths contract (one shared implementation:
    generate.py:validate_prompt_lengths), with a zero placeholder for
    aligned batches so the compiled signature is uniform."""
    if prompt_lengths is None:
        return jnp.zeros((B,), jnp.int32)
    from .generate import validate_prompt_lengths

    return validate_prompt_lengths(prompt_lengths, B, P)


def _finish_spec(prompt, toks, stats, eos_id, return_stats, ragged=False):
    """Shared tail: conventional eos-fill on the finished buffer, prompt
    concat (aligned batches; ragged returns only the new tokens, the
    generate() contract), optional acceptance-stats dict."""
    if eos_id is not None:
        # Everything after a row's first eos becomes eos.
        seen = jnp.cumsum((toks == eos_id).astype(jnp.int32), axis=1)
        fill = (seen - (toks == eos_id).astype(jnp.int32)) > 0
        toks = jnp.where(fill, jnp.int32(eos_id), toks)
    out = toks if ragged else jnp.concatenate([prompt, toks], axis=1)
    if return_stats:
        return out, {"macro_steps": stats[:, 0], "accepted": stats[:, 1]}
    return out


def generate_lookup(params: dict, cfg: LlamaConfig, prompt,
                    max_new_tokens: int, *, gamma: int = 4, ngram: int = 2,
                    temperature: float = 0.0,
                    key: Optional[jax.Array] = None,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    eos_id: Optional[int] = None,
                    prompt_lengths=None,
                    return_stats: bool = False):
    """Prompt-lookup speculative generation: no draft model — proposals
    are copied from the sequence's own history (continue the latest
    earlier occurrence of the current ``ngram``-gram,
    :func:`_lookup_propose`) and verified by the target's chunk forward.
    The drafter costs a few gathers, so ANY acceptance is pure profit;
    repetitive workloads (code, extraction, quoting) accept a lot.  Same
    guarantees as :func:`generate_speculative`: greedy output matches
    ``generate()`` up to bf16 argmax near-ties between the chunk and
    stepwise forwards; sampling preserves the target
    distribution (deterministic proposals are the ``p_D = one-hot``
    special case of the same rejection rule).  Same contract and
    restrictions otherwise (aligned or ragged ``prompt_lengths``
    batches; dense or provably-dropless MoE; sliding-window models run
    through full caches).
    """
    B, P = prompt.shape
    _validate_spec_args(max_new_tokens, gamma, (cfg, "target"))
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    lengths = _validate_lengths(prompt_lengths, B, P)
    if key is None:
        key = jax.random.PRNGKey(0)
    from .llama import resolve_longrope

    cfg = resolve_longrope(cfg, P + max_new_tokens)  # logical horizon,
    # matching generate()'s regime for the same request (spec decode's
    # output-equivalence contract); the gamma headroom below is scratch.
    max_len = P + max_new_tokens + gamma
    if max_len == cfg.sliding_window:
        # Dodge chunk_decode_step's rolling-cache shape heuristic (a FULL
        # cache of exactly window slots is indistinguishable from the
        # rolling layout); the extra slot is masked out of attention.
        max_len += 1
    run = _compiled_lookup(cfg, B, P, max_new_tokens, max_len, int(gamma),
                           int(ngram), float(temperature), top_k, top_p,
                           ragged=prompt_lengths is not None)
    toks, stats = run(params, prompt, key, lengths)
    return _finish_spec(prompt, toks, stats, eos_id, return_stats,
                        ragged=prompt_lengths is not None)
