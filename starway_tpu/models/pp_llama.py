"""Pipeline-parallel Llama training: embed + staged decoder pipeline + head.

End-to-end 1F1B over the ``pp`` mesh axis with ALL parameters receiving
gradients: token embedding (outside the pipeline, chained through the
input-cotangent the schedule emits), n_layers/n_stages decoder blocks per
stage (parallel/pipeline.py's collective 1F1B), and the head (final norm +
lm_head, differentiated inside the last stage's loss).  The decoder block
is the same :func:`~starway_tpu.models.llama.decoder_layer` the scan
forward uses — one source of truth for the math.

Layout: parameters live PRE-SPLIT in pipeline form (``pp_split_params``):

    {"embed": [V, D],                      # replicated
     "stages": {name: [n_stages, L/S, ...]},  # leading dim sharded over pp
     "head": {"final_norm": [D], "lm_head": [D, V]}}  # replicated

so optimizer state shards the same way and no reshuffling happens per step.
``pp_merge_params`` restores the flat layout (for generation/eval).

Reference hook: the reference's nearest analogue is the streaming-duplex
"model parallelism" traffic pattern (/root/reference/benchmark.md:91-99);
the schedule itself is the TPU build's own.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .llama import (LlamaConfig, cfg_rope_tables, decoder_layer,
                    embed_tokens, head_logits, resolve_attn_fn, token_ce)
from ..parallel.pipeline import make_pipeline_train


def pp_split_params(params: dict, n_stages: int) -> dict:
    """Flat init_params tree -> pipeline layout (see module docstring)."""
    layers = params["layers"]
    lead = jax.tree_util.tree_leaves(layers)[0].shape[0]
    if lead % n_stages:
        raise ValueError(f"n_layers={lead} not divisible by {n_stages} stages")
    stages = jax.tree_util.tree_map(
        lambda a: a.reshape(n_stages, lead // n_stages, *a.shape[1:]), layers)
    return {
        "embed": params["embed"],
        "stages": stages,
        "head": {"final_norm": params["final_norm"],
                 "lm_head": params["lm_head"]},
    }


def pp_merge_params(pp_params: dict) -> dict:
    """Pipeline layout -> flat init_params tree."""
    stages = pp_params["stages"]
    lead = jax.tree_util.tree_leaves(stages)[0]
    n_layers = lead.shape[0] * lead.shape[1]
    return {
        "embed": pp_params["embed"],
        "layers": jax.tree_util.tree_map(
            lambda a: a.reshape(n_layers, *a.shape[2:]), stages),
        "final_norm": pp_params["head"]["final_norm"],
        "lm_head": pp_params["head"]["lm_head"],
    }


def _moe_stage_template(cfg: LlamaConfig) -> dict:
    """Shape-only skeleton of one MoE stage tree (keys mirror
    llama.py:init_params' layer dict for ``cfg``; leaf values are
    placeholders) — enough structure for :func:`_expert_leaf_spec` /
    :func:`pp_stage_specs` to build spec trees before any real params
    exist.  Must track init_params' key set exactly (tree_map over
    mismatched structures raises inside shard_map otherwise)."""
    t = {
        "wq": 0, "wk": 0, "wv": 0, "wo": 0,
        "attn_norm": 0, "mlp_norm": 0,
        "moe": {"router": 0, "w_in": 0, "w_out": 0},
    }
    if cfg.moe_swiglu:
        t["moe"]["w_gate"] = 0
    if cfg.attn_bias:
        t.update(bq=0, bk=0, bv=0)
    return t


def _expert_leaf_spec(stages: dict):
    """Bool pytree matching ``stages``: True on the expert-table leaves
    (``moe/w_in``, ``moe/w_out``, swiglu ``moe/w_gate``) whose rows are
    per-expert, False on everything else (including the
    replicated-per-device router)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _a: any(
            getattr(k, "key", None) == "moe" for k in path) and any(
            getattr(k, "key", None) in ("w_in", "w_out", "w_gate")
            for k in path),
        stages)


def pp_stage_specs(stages: dict, axis_name: str = "pp",
                   ep_axis: Optional[str] = None):
    """PartitionSpecs for the ``stages`` subtree: every leaf shards its
    leading (stage) dim over ``axis_name``; with ``ep_axis``, the expert
    tables ``[S, L/S, E, ...]`` additionally shard their expert dim."""
    if ep_axis is None:
        return jax.tree_util.tree_map(lambda _a: P(axis_name), stages)
    return jax.tree_util.tree_map(
        lambda is_exp: P(axis_name, None, ep_axis) if is_exp
        else P(axis_name),
        _expert_leaf_spec(stages))


def pp_param_specs(pp_params: dict, axis_name: str = "pp",
                   ep_axis: Optional[str] = None) -> dict:
    """Per-leaf PartitionSpec tree for the pipeline layout (same shape as
    ``pp_params``, consumable by :func:`~starway_tpu.parallel.shard_tree`):
    stage leaves shard their leading (stage) dim over ``axis_name``
    (expert tables additionally over ``ep_axis`` when given),
    embed/head replicate."""
    return {
        "embed": P(),
        "stages": pp_stage_specs(pp_params["stages"], axis_name, ep_axis),
        "head": jax.tree_util.tree_map(lambda _a: P(), pp_params["head"]),
    }


def shard_pp_params(pp_params: dict, mesh, axis_name: str = "pp",
                    ep_axis: Optional[str] = None) -> dict:
    from ..parallel.fsdp import shard_tree

    return shard_tree(pp_params, mesh,
                      pp_param_specs(pp_params, axis_name, ep_axis))


def ppv_split_params(params: dict, n_stages: int, n_chunks: int) -> dict:
    """Flat init_params tree -> INTERLEAVED pipeline layout: stages get a
    leading ``[V, S, L/(V*S), ...]`` shape where ``stages[c, d]`` holds
    virtual stage ``c*S + d``'s layers (parallel/interleaved.py's
    placement).  ``pp_split_params``'s [V*S]-leading layout reshapes
    straight in (virtual stage v = flat index v)."""
    flat = pp_split_params(params, n_stages * n_chunks)
    return {
        "embed": flat["embed"],
        "stages": jax.tree_util.tree_map(
            lambda a: a.reshape(n_chunks, n_stages, *a.shape[1:]),
            flat["stages"]),
        "head": flat["head"],
    }


def ppv_merge_params(ppv_params: dict) -> dict:
    stages = ppv_params["stages"]
    return pp_merge_params({
        "embed": ppv_params["embed"],
        "stages": jax.tree_util.tree_map(
            lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]),
            stages),
        "head": ppv_params["head"],
    })


def ppv_param_specs(ppv_params: dict, axis_name: str = "pp") -> dict:
    """Specs for the interleaved layout: stage leaves shard dim 1 (the
    device dim) over ``axis_name``; dim 0 (the chunk dim) is device-local
    and stays unsharded; embed/head replicate."""
    return {
        "embed": P(),
        "stages": jax.tree_util.tree_map(lambda _a: P(None, axis_name),
                                         ppv_params["stages"]),
        "head": jax.tree_util.tree_map(lambda _a: P(), ppv_params["head"]),
    }


def shard_ppv_params(ppv_params: dict, mesh, axis_name: str = "pp") -> dict:
    from ..parallel.fsdp import shard_tree

    return shard_tree(ppv_params, mesh, ppv_param_specs(ppv_params, axis_name))


def make_pp_llama_train(mesh, cfg: LlamaConfig, *, axis_name: str = "pp",
                        n_micro: int, attn_fn: Optional[Callable] = None,
                        n_chunks: int = 1, dp_axis: Optional[str] = None,
                        ep_axis: Optional[str] = None):
    """Build ``step(pp_params, batch) -> (loss, grads)``, jit-compiled.

    ``batch``: [B, S+1] token ids, B divisible by ``n_micro``.  ``grads``
    has the pipeline layout of ``pp_params`` — feed it straight to optax.

    MoE configs (``cfg.n_experts > 0``) pipeline too: each stage owns its
    layers' expert tables and routes per microbatch (capacity from the
    microbatch's token count), the per-stage balance aux chains through
    the schedule exactly like the main loss (pipeline.py ``with_aux``),
    and the step's loss matches the sequential
    ``mean_microbatch(CE + coef * aux / n_layers)`` semantics of
    llama.py's ``loss_fn``.  Without ``ep_axis`` the experts are
    stage-LOCAL (wholly resident on the stage's device — fine until the
    expert tables outgrow one chip).  With ``ep_axis`` (a pp x ep mesh),
    each stage's expert tables shard over the ep sub-axis, tokens shard
    over ep like a second dp axis, and the dispatch rides
    :func:`~starway_tpu.models.moe.sharded_switch_moe`'s explicit
    ``all_to_all`` — expert-table gradients get expert-aware reduction
    (no pmean across ep; the all-to-all transpose already summed).
    Interleaved MoE (``n_chunks > 1``) runs with stage-LOCAL experts
    (the virtual-chunk schedule chains aux the same way); ep sharding
    composes with the plain schedule only.

    ``n_chunks > 1``: the INTERLEAVED 1F1B schedule
    (parallel/interleaved.py) with that many virtual chunks per device;
    ``pp_params`` must then be in ``ppv_split_params`` layout
    (stages ``[V, S, L/(V*S), ...]``).  Worth it when stages are many and
    microbatches few — see interleaved.py's fill-cost accounting.

    ``dp_axis``: compose either schedule with data parallelism on a
    pp x dp mesh (parallel/pipeline.py:dp_compose): each microbatch's rows
    shard over dp (``B / n_micro`` must divide by the dp size), grads ride
    one dp pmean, and the embedding gradient chains from the 1/ndp-scaled
    input cotangents — same training math, smaller per-device batch.
    """
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers % (n_stages * n_chunks):
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"{n_stages} stages x {n_chunks} chunks")
    moe = cfg.n_experts > 0
    if moe and n_chunks > 1 and ep_axis is not None:
        raise NotImplementedError(
            "interleaved (n_chunks > 1) MoE is stage-local only; ep "
            "sharding composes with the plain 1F1B schedule")
    if ep_axis is not None and not moe:
        raise ValueError("ep_axis given but cfg.n_experts == 0")
    attn = resolve_attn_fn(cfg, attn_fn)

    if moe and ep_axis is not None:
        from .moe import sharded_switch_moe

        def moe_fn(x, router_w, w_in, w_out, w_gate=None):
            # Already inside the pipeline's shard_map: the ep axis is
            # live, w_in/w_out (and swiglu w_gate) leaves are the local
            # [E/ep, D, F] shard.
            return sharded_switch_moe(
                x, router_w, w_in, w_out, ep_axis, w_gate=w_gate,
                capacity_factor=cfg.moe_capacity_factor, k=cfg.moe_top_k)
    else:
        moe_fn = None  # decoder_layer defaults to stage-local switch_moe

    def run_layers(local, h):
        """Scan ``h`` through a [L_local, ...] slice of the layer tree.
        MoE: also return the slice's balance aux, scaled to llama.py
        loss_fn's semantics (coef * sum / n_layers) so stage aux terms
        sum to the sequential loss's term."""
        cos, sin = cfg_rope_tables(cfg, h.shape[1])

        def body(carry, lp):
            hh, aux = carry
            hh, a, _k, _v, _stats = decoder_layer(lp, hh, cfg, cos, sin,
                                                  attn, moe_fn=moe_fn)
            return (hh, aux + a), None

        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)), local)
        if moe:
            return h, aux * (cfg.moe_aux_coef / cfg.n_layers)
        return h

    def stage_fn(stage_lp, h):
        # Inside shard_map the stage tree keeps a leading local dim of 1
        # ([1, L/S, ...]); peel it so the scan runs over this stage's L/S
        # layers (vjp through the indexing restores the dim on gradients).
        return run_layers(jax.tree_util.tree_map(lambda a: a[0], stage_lp), h)

    def chunk_fn(chunk_lp, h):
        # Interleaved path: the schedule's chunk_params already peeled the
        # leading dims -- chunk_lp leaves are [L/(V*S), ...].
        return run_layers(chunk_lp, h)

    def loss_fn(head, y, target):
        logits = head_logits(y, head["final_norm"], head["lm_head"],
                             cfg.norm_eps)
        return token_ce(logits, target)

    if n_chunks > 1:
        from ..parallel.interleaved import make_interleaved_pipeline_train

        grad_step = make_interleaved_pipeline_train(
            mesh, chunk_fn, loss_fn, axis_name, n_chunks=n_chunks,
            n_micro=n_micro, with_head=True, return_dx=True,
            dp_axis=dp_axis, with_aux=moe)
    else:
        if moe:
            # Specs for leaves sharded beyond the stage dim (expert
            # tables over ep) ride through to shard_map; the expert mask
            # drives the ep-aware gradient reduction.  Built from a
            # shape-only template tree (leaf VALUES are ignored).
            template = _moe_stage_template(cfg)
            kw = {"with_aux": True}
            if ep_axis is not None:
                kw.update(
                    ep_axis=ep_axis,
                    expert_spec=_expert_leaf_spec(template),
                    param_specs=pp_stage_specs(template, axis_name, ep_axis))
        else:
            kw = {}
        grad_step = make_pipeline_train(mesh, stage_fn, loss_fn, axis_name,
                                        with_head=True, return_dx=True,
                                        dp_axis=dp_axis, **kw)

    def step(pp_params, batch):
        tokens, targets = batch[:, :-1], batch[:, 1:]
        B, S = tokens.shape
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        n_data = 1
        for a in (dp_axis, ep_axis):
            if a is not None:
                n_data *= mesh.shape[a]
        if mb % n_data:
            raise ValueError(
                f"microbatch rows ({mb} = {B}/{n_micro}) not divisible by "
                f"the data-sharding size {n_data} (dp x ep)")
        D = pp_params["embed"].shape[1]

        h0 = embed_tokens(pp_params, tokens, cfg).reshape(n_micro, mb, S, D)
        tgt = targets.reshape(n_micro, mb, S)
        loss, dstages, dhead, dh0 = grad_step(
            pp_params["stages"], pp_params["head"], h0, tgt)

        # Chain the input cotangent into the embedding table: scatter-add
        # d h0 over the token ids (B*S rows; reshape orders match h0's).
        # embed_tokens scales h0 by sqrt(D) on scaled_embed configs
        # (Gemma), so the chain rule carries the same factor back.
        dh0 = dh0.reshape(-1, D)
        if cfg.scaled_embed:
            dh0 = dh0 * (D ** 0.5)
        dembed = jnp.zeros(pp_params["embed"].shape, jnp.float32).at[
            tokens.reshape(-1)].add(dh0)

        grads = {"embed": dembed, "stages": dstages, "head": dhead}
        return loss, grads

    return jax.jit(step)
