"""Model families built on the framework's device plane.

The reference moves opaque buffers; the configs in BASELINE.json ground them
in real workloads ("Llama-3 8B activation/grad transfer between TPU hosts").
This package provides the flagship Llama family used by the benchmarks, the
DP-exchange demos, and the graft entry's multichip training step.
"""

from .llama import (
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
)
from .hf_convert import config_from_hf, params_from_hf
from .pp_llama import (
    make_pp_llama_train,
    pp_merge_params,
    pp_param_specs,
    pp_split_params,
    ppv_merge_params,
    ppv_split_params,
    shard_pp_params,
    shard_ppv_params,
)
from .beam import generate_beam
from .generate import (generate, init_cache, init_rolling_cache, prefill,
                       prefill_rolling)
from .paged import PagedSlotServer, init_paged_pool, paged_decode_step
from .remote_serving import RemoteGenerateSession, RemoteSlotServer
from .serving import SlotServer
from .trainer import Trainer
from .speculative import (chunk_decode_step, draft_from_truncation,
                          generate_lookup, generate_speculative)

__all__ = [
    "LlamaConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
    "param_specs",
    "config_from_hf",
    "params_from_hf",
    "make_pp_llama_train",
    "pp_split_params",
    "pp_merge_params",
    "pp_param_specs",
    "shard_pp_params",
    "ppv_split_params",
    "ppv_merge_params",
    "shard_ppv_params",
    "PagedSlotServer",
    "RemoteGenerateSession",
    "RemoteSlotServer",
    "SlotServer",
    "Trainer",
    "generate",
    "init_cache",
    "init_rolling_cache",
    "prefill",
    "prefill_rolling",
    "chunk_decode_step",
    "draft_from_truncation",
    "generate_beam",
    "generate_lookup",
    "generate_speculative",
]
