"""Serving over the transport: tagged requests in, token streams out.

The repo's two halves meet here (VERDICT r4 #2): the async tag-matched
P2P transport — the reference's actual product surface
(/root/reference/src/bindings/main.cpp:370,1172 — tag send/recv over
endpoint connections) — carries the serving stack's actual workload.
Requests arrive as tagged messages on a :class:`~starway_tpu.Server`,
:class:`~starway_tpu.models.serving.SlotServer` admits them into its
continuous batch, and each request's tokens stream back per decode chunk
over the same connection.  Works over every data plane behind the one
worker contract (in-process, TCP, shared-memory rings, the C++ engine) —
pinned by tests/test_serve_remote.py's transport matrix.

Wire protocol — all payloads are little-endian int32 arrays; the 64-bit
tag's top byte is the message type (tag routing, reference-style):

====== ========= ================ =======================================
type   direction tag              payload
====== ========= ================ =======================================
0xA1   S -> C    ASSIGN           [client_id, max_prompt_tokens] — sent
                                  on accept; identity for request tags +
                                  the server's request-size limit
0xA2   C -> S    REQUEST | cid    [nonce, max_new, n, prompt x n]
0xA3   S -> C    TOKENS | nonce   [nonce, status, count, tokens x count]
                                  status: 0 = streaming, 1 = done,
                                  2 = aborted (rejected or cancelled)
0xA4   C -> S    CANCEL | cid     [nonce] — abort that request; its slot
                                  frees on the next decode step
====== ========= ================ =======================================

Routing: the matcher reports a completed wildcard recv's SENDER TAG, not
its endpoint, so the request tag carries the server-assigned client_id
(low 32 bits) and the bridge maps it back to the accepted endpoint.  The
token stream needs no client id in its tag — it rides the requesting
client's own connection — so the low bits carry the client-chosen nonce,
letting one client run many concurrent generates.

The per-chunk TOKENS messages for one request are FIFO on one
connection (the engine preserves per-connection send order), so the
client just accumulates until ``done``.  Send completion is local
(CLAUDE.md contract): mid-stream no flush is needed (a dead client just
fails its pending sends, logged and dropped), but serve() flushes once
before returning so a close right after cannot cancel the final chunks
out from under still-reading clients.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Optional

import numpy as np

from ..api import Client, Server
from .serving import SlotServer

logger = logging.getLogger("starway.serve_remote")

TAG_TYPE_SHIFT = 56
TAG_ASSIGN = 0xA1 << TAG_TYPE_SHIFT
TAG_REQUEST = 0xA2 << TAG_TYPE_SHIFT
TAG_TOKENS = 0xA3 << TAG_TYPE_SHIFT
TAG_CANCEL = 0xA4 << TAG_TYPE_SHIFT
TYPE_MASK = 0xFF << TAG_TYPE_SHIFT

STATUS_STREAMING, STATUS_DONE, STATUS_ABORTED = 0, 1, 2
FULL_MASK = (1 << 64) - 1
_ID_MASK = (1 << 32) - 1


def _wire(words) -> np.ndarray:
    """int32 payload -> the uint8 byte view the transport sends."""
    return np.ascontiguousarray(np.asarray(words, np.int32)).view(np.uint8)


def _recv_buf(n_words: int) -> np.ndarray:
    """Receive target (the transport requires uint8); read back with
    ``buf.view(np.int32)``."""
    return np.empty(4 * n_words, np.uint8)


class RemoteSlotServer:
    """Serve a :class:`SlotServer` behind a transport :class:`Server`.

    >>> bridge = RemoteSlotServer(slot_server)
    >>> bridge.server.listen("127.0.0.1", port)
    >>> await bridge.serve()            # until bridge.stop() from a task

    Request ingestion is callback-chained on the engine thread (each
    completed wildcard recv immediately re-posts); the asyncio drive loop
    drains them into ``SlotServer.submit`` and advances decode chunks in
    an executor so the event loop keeps absorbing arrivals while the
    device computes.  Token emission rides ``SlotServer.on_tokens``.
    """

    def __init__(self, slot_server: SlotServer, server: Optional[Server] = None,
                 *, max_prompt_tokens: int = 8192):
        if slot_server.on_tokens is not None:
            raise ValueError("slot_server.on_tokens is already claimed")
        slot_server.on_tokens = self._on_tokens
        self.slot = slot_server
        self.server = server if server is not None else Server()
        self.max_prompt_tokens = int(max_prompt_tokens)
        self._eps: dict[int, object] = {}      # client_id -> endpoint
        self._next_cid = 1
        self._rid_route: dict[int, tuple] = {}  # rid -> (cid, nonce)
        self._emissions: list = []              # (rid, tokens, done)
        self._requests: deque = deque()         # (sender_tag, payload copy)
        self._unassigned: deque = deque()       # cids awaiting their ASSIGN
        self._dead_cids: deque = deque()        # send-failed clients to drop
        self._stopping = False
        self._closed = False
        self._recv_posted = False
        self._cancels: deque = deque()          # (cid, nonce) to abort
        # Cancels that arrived BEFORE their request was submitted (both
        # can land in the queues during one multi-second decode step and
        # cancels drain first): consulted at submit time so the request
        # is rejected instead of the cancel being silently lost.
        # Insertion-ordered and bounded: a cancel for a nonce that never
        # shows up must not leak.
        self._pre_cancels: dict[tuple, bool] = {}
        self.server.set_accept_cb(self._on_accept)

    # ------------------------------------------------- engine-thread side
    def _on_accept(self, ep) -> None:
        cid = self._next_cid
        self._next_cid += 1
        self._eps[cid] = ep
        # The ASSIGN cannot be sent from here: on the in-process path the
        # accept callback fires inline DURING the client's connect, before
        # the client worker reaches RUNNING, and the send would die with
        # "peer closed".  The serve loop flushes it (the client's
        # register() recv waits however late it lands).
        self._unassigned.append(cid)

    def _post_typed_recv(self, tag: int, n_words: int, on_msg) -> None:
        """One self-re-posting wildcard recv chain per message type.
        ``on_msg(sender_tag, words)`` runs on the engine thread and must
        only enqueue.  Failures re-post too: a failed recv is consumed by
        the matcher, so without the re-post one bad message (e.g. a
        truncated oversized request) would permanently halt that type's
        intake."""
        buf = _recv_buf(n_words)

        def done(stag, length, buf=buf):
            try:
                on_msg(int(stag), buf.view(np.int32)[:length // 4].copy())
            except Exception:
                # A sink crash must not break the re-post chain.
                logger.exception("recv sink failed (tag type %x)",
                                 tag >> TAG_TYPE_SHIFT)
            if not self._closed:
                self._post_typed_recv(tag, n_words, on_msg)

        def fail(reason):
            # Expected at close ("cancel...") — not the CANCEL message
            # type, but the engine's op-cancellation reason string.
            if self._closed or "cancel" in reason:
                return
            logger.warning("recv (tag type %x) failed: %s",
                           tag >> TAG_TYPE_SHIFT, reason)
            try:
                self._post_typed_recv(tag, n_words, on_msg)
            except Exception:
                pass  # worker shutting down

        self.server.recv(buf, tag, TYPE_MASK, done, fail)

    def _post_request_recv(self) -> None:
        self._post_typed_recv(
            TAG_REQUEST, 3 + self.max_prompt_tokens,
            lambda stag, words: self._requests.append((stag, words)))

    def _post_cancel_recv(self) -> None:
        def on_msg(stag, words):
            if len(words) >= 1:  # an empty CANCEL payload is just noise
                self._cancels.append((stag & _ID_MASK, int(words[0])))

        self._post_typed_recv(TAG_CANCEL, 1, on_msg)

    def _on_tokens(self, rid: int, tokens: list, done: bool) -> None:
        # Fires inside SlotServer.step() (executor thread); the drive
        # loop flushes after the step returns, preserving order.
        self._emissions.append((rid, tokens, done))

    # --------------------------------------------------- loop-thread side
    def _drop_dead_clients(self) -> None:
        while self._dead_cids:
            cid = self._dead_cids.popleft()
            if self._eps.pop(cid, None) is not None:
                logger.warning("dropping client %d (send failed)", cid)
            for rid, (rcid, _nonce) in list(self._rid_route.items()):
                if rcid == cid:
                    # Decoding for a peer that will never read the
                    # stream is wasted chip time: free the slot too.
                    self.slot.cancel(rid)
                    del self._rid_route[rid]
            for k in [k for k in self._pre_cancels if k[0] == cid]:
                self._pre_cancels.pop(k, None)  # free the stash budget

    def _drain_cancels(self) -> None:
        while self._cancels:
            cid, nonce = self._cancels.popleft()
            for rid, (rcid, rnonce) in list(self._rid_route.items()):
                if rcid == cid and rnonce == nonce:
                    self.slot.cancel(rid)
                    del self._rid_route[rid]
                    # Closure marker so a still-listening generate()
                    # terminates instead of awaiting forever.
                    self._send_chunk(cid, nonce, [], STATUS_ABORTED)
                    break
            else:
                if cid not in self._eps:
                    continue  # junk/stale cid: nothing to stash for
                # Not routed yet: the REQUEST may still be in flight
                # behind this cancel.  Stash so submit rejects it.
                # Budget is PER CLIENT so one cancel-spraying peer
                # cannot evict another client's genuine pre-cancel.
                self._pre_cancels[(cid, nonce)] = True
                mine = [k for k in self._pre_cancels if k[0] == cid]
                for k in mine[:max(0, len(mine) - 64)]:
                    self._pre_cancels.pop(k, None)

    def _flush_assigns(self) -> None:
        while self._unassigned:
            cid = self._unassigned.popleft()
            ep = self._eps.get(cid)
            if ep is None:
                continue
            # max_prompt_tokens rides along so the client can reject an
            # oversized prompt LOCALLY — sent to the server it would
            # truncate the wildcard recv before the nonce is parsed,
            # leaving nothing to reply to.
            self.server.send(
                ep, _wire([cid, self.max_prompt_tokens]), TAG_ASSIGN,
                lambda: None,
                lambda reason, cid=cid: logger.warning(
                    "assign to client %d failed: %s", cid, reason))

    def _drain_requests(self) -> int:
        n = 0
        while self._requests:
            stag, arr = self._requests.popleft()
            cid = stag & _ID_MASK
            if cid not in self._eps:
                # No endpoint to reply over; the sender is gone or buggy.
                logger.warning("request from unknown client id %d", cid)
                continue
            if len(arr) < 3 or len(arr) != 3 + int(arr[2]):
                logger.warning("malformed request from client %d "
                               "(%d words)", cid, len(arr))
                if len(arr) >= 1:
                    # The nonce survived: reject fatally instead of
                    # leaving the client's generate() awaiting forever.
                    self._send_chunk(cid, int(arr[0]), [], STATUS_ABORTED)
                continue
            nonce, max_new, n_tok = int(arr[0]), int(arr[1]), int(arr[2])
            if self._pre_cancels.pop((cid, nonce), False):
                # Cancelled before it was ever submitted (the CANCEL
                # overtook the REQUEST in the drain order).
                self._send_chunk(cid, nonce, [], STATUS_ABORTED)
                continue
            try:
                rid = self.slot.submit(arr[3:3 + n_tok], max_new)
            except (ValueError, KeyError) as e:
                # Reject without killing the serve loop: an empty, fatal
                # "done" stream tells the client this request is over.
                logger.warning("rejected request from client %d: %s",
                               cid, e)
                self._send_chunk(cid, nonce, [], STATUS_ABORTED)
                continue
            self._rid_route[rid] = (cid, nonce)
            n += 1
        return n

    def _send_chunk(self, cid: int, nonce: int, tokens: list,
                    status) -> None:
        ep = self._eps.get(cid)
        if ep is None:
            return
        def failed(reason, cid=cid):
            # Engine-thread callback: only enqueue; the serve loop drops
            # the endpoint and its routes (no cross-thread dict mutation).
            logger.warning("token chunk to client %d failed: %s",
                           cid, reason)
            self._dead_cids.append(cid)

        self.server.send(
            ep, _wire([nonce, int(status), len(tokens), *tokens]),
            TAG_TOKENS | nonce, lambda: None, failed)

    def _flush_emissions(self) -> None:
        emissions, self._emissions = self._emissions, []
        for rid, tokens, done in emissions:
            route = self._rid_route.get(rid)
            if route is None:
                continue  # cancelled mid-step; stream already closed
            cid, nonce = route
            self._send_chunk(cid, nonce, tokens,
                             STATUS_DONE if done else STATUS_STREAMING)
            if done:
                del self._rid_route[rid]

    async def serve(self, *, idle_sleep: float = 0.002) -> None:
        """Drive until :meth:`stop` AND all in-flight work has drained.
        The server must be listening (posting a recv needs a RUNNING
        worker), so call ``bridge.server.listen(...)`` first."""
        if not self._recv_posted:
            self._post_request_recv()
            self._post_cancel_recv()
            self._recv_posted = True
        loop = asyncio.get_running_loop()
        while not (self._stopping and not self.slot.busy
                   and not self._requests):
            self._drop_dead_clients()
            self._drain_cancels()
            self._flush_assigns()
            self._drain_requests()
            if self.slot.busy:
                await loop.run_in_executor(None, self.slot.step)
                self._flush_emissions()
            else:
                await asyncio.sleep(idle_sleep)
        self._flush_emissions()
        # Send completion is LOCAL (CLAUDE.md); a close right after serve()
        # could cancel the final TOKENS chunks still in flight and hang
        # mid-stream clients — the flush is the delivery barrier.
        try:
            await self.server.aflush()
        except Exception as e:  # worker already closing
            logger.warning("final flush failed: %s", e)

    def stop(self) -> None:
        """Finish in-flight requests, then let serve() return."""
        self._stopping = True

    async def aclose(self) -> None:
        self._closed = True
        await self.server.aclose()


class RemoteGenerateSession:
    """Client-side counterpart: submit prompts, await token streams.

    >>> session = await RemoteGenerateSession.aconnect(addr, port)
    >>> tokens = await session.generate(prompt, max_new_tokens=32)

    ``generate`` calls may run concurrently on one session (distinct
    nonces route the streams); tokens accumulate per decode chunk, so
    wrapping the recv loop yields true streaming if a caller wants it.
    """

    class Handle:
        """Out-param for generate(): carries the request nonce so the
        caller can cancel() a stream it no longer wants."""

        nonce: Optional[int] = None

    def __init__(self, client: Client):
        self.client = client
        self.client_id: Optional[int] = None
        self.server_max_prompt: Optional[int] = None
        self._nonce = 0

    @classmethod
    async def aconnect(cls, addr: str, port: int) -> "RemoteGenerateSession":
        client = Client()
        await client.aconnect(addr, port)
        session = cls(client)
        await session.register()
        return session

    async def register(self) -> int:
        """Receive the server-assigned client id (sent on accept)."""
        buf = _recv_buf(2)
        await self.client.arecv(buf, TAG_ASSIGN, FULL_MASK)
        words = buf.view(np.int32)
        self.client_id = int(words[0])
        self.server_max_prompt = int(words[1])
        return self.client_id

    async def generate(self, prompt, max_new_tokens: int,
                       *, max_chunk_tokens: int = 4096,
                       on_tokens=None, handle: "Optional[Handle]" = None) -> np.ndarray:
        """Round-trip one request; returns the generated tokens.

        ``on_tokens(list)``: optional per-chunk streaming callback.
        ``handle``: a :class:`Handle` that receives the request nonce
        before the request is sent — pass it to :meth:`cancel` from
        another task to abort the stream server-side."""
        if self.client_id is None:
            raise RuntimeError("call register() (or aconnect()) first")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if (self.server_max_prompt is not None
                and len(prompt) > self.server_max_prompt):
            # Server-side this would truncate the request recv before the
            # nonce is parsed — unanswerable; reject here instead.
            raise ValueError(
                f"prompt ({len(prompt)} tokens) exceeds the server's "
                f"request limit ({self.server_max_prompt})")
        nonce = self._nonce
        self._nonce += 1
        if handle is not None:
            handle.nonce = nonce
        req = _wire(np.concatenate([
            np.asarray([nonce, int(max_new_tokens), len(prompt)], np.int32),
            prompt]))
        await self.client.asend(req, TAG_REQUEST | self.client_id)
        out: list = []
        while True:
            buf = _recv_buf(3 + max_chunk_tokens)
            await self.client.arecv(buf, TAG_TOKENS | nonce, FULL_MASK)
            words = buf.view(np.int32)
            count, status = int(words[2]), int(words[1])
            chunk = [int(t) for t in words[3:3 + count]]
            out.extend(chunk)
            if chunk and on_tokens is not None:
                on_tokens(chunk)
            if status == STATUS_ABORTED:
                raise ValueError(
                    "request rejected or cancelled by the server "
                    f"(after {len(out)} tokens); rejections mean "
                    "prompt/max_new exceeded the server's max_len")
            if status == STATUS_DONE:
                return np.asarray(out, np.int32)

    async def cancel(self, handle: "Handle") -> None:
        """Abort the stream identified by ``handle`` server-side: its
        slot frees on the next decode step and the stream terminates
        with an aborted marker (the awaiting generate() raises)."""
        if handle.nonce is None:
            raise ValueError("handle was never passed to generate()")
        await self.client.asend(_wire([handle.nonce]),
                                TAG_CANCEL | self.client_id)

    async def aclose(self) -> None:
        await self.client.aclose()
