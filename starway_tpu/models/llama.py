"""Compact pure-JAX Llama family (RMSNorm + RoPE + GQA + SwiGLU).

TPU-first construction:

* layer parameters are *stacked* on a leading ``[n_layers, ...]`` axis and
  the forward pass is a single ``lax.scan`` over them -- one compiled layer
  body regardless of depth, optionally rematerialised (``cfg.remat``) to
  trade FLOPs for HBM;
* attention is pluggable: :func:`~starway_tpu.ops.attention.blockwise_attention`
  single-device, or sequence-parallel ring attention over an ICI mesh axis
  (:func:`make_sharded_attn`), keeping long context first-class;
* matmuls run in ``cfg.dtype`` (bfloat16 on TPU -> MXU) with f32 accumulators
  in the softmax/norm chains;
* sharding is declarative: :func:`param_specs` gives the GSPMD PartitionSpec
  tree (tp on head/ff dims, replicated norms) and XLA inserts the
  collectives.

Presets include ``llama3-8b`` (the BASELINE config 5 workload shape) and
scaled-down variants for tests and the graft entry.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..ops.attention import blockwise_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False
    # Sliding-window (Mistral-style) attention: each position attends to
    # the last `sliding_window` tokens only.  None = full causal.
    sliding_window: Optional[int] = None
    # Mixture-of-experts FFN (0 = dense SwiGLU).  Experts shard over the
    # mesh "ep" axis (models/moe.py).
    n_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    moe_top_k: int = 1
    # SwiGLU experts (Mixtral family): adds a w_gate [L, E, D, F] leaf and
    # switches _expert_ffn to silu(x@w_gate) * (x@w_in) @ w_out.
    moe_swiglu: bool = False
    # Gated-MLP activation: "silu" (Llama SwiGLU) or "gelu_tanh" (Gemma
    # GeGLU, = HF's gelu_pytorch_tanh).
    mlp_act: str = "silu"
    # Gemma-style sqrt(d_model) scaling of the token embedding OUTPUT
    # (the tied lm_head reads the UNSCALED table, so this cannot fold
    # into the weights).
    scaled_embed: bool = False
    # KV-cache storage: "none" keeps compute_dtype; "int8" stores the cache
    # int8 with per-token scales (ops/quantize.py) — half the HBM bytes on
    # the bandwidth-bound decode stream, double the servable context.
    kv_quant: str = "none"
    # Per-head dim override.  None derives d_model // n_heads (the classic
    # tie, recomputed on every access so dataclasses.replace(n_heads=...)
    # can never carry a stale value); modern HF checkpoints may pin it
    # independently (q/k/v project to n_heads * head_dim != d_model) —
    # every projection/reshape in this module keys off cfg.head_dim.
    head_dim_override: Optional[int] = None
    # Per-head q/k/v projection biases (Qwen2-family checkpoints; Llama/
    # Mistral have none).  Adds bq/bk/bv [L, H*hd] leaves to the layer
    # tree; consumers key off the LEAVES' presence (qkv_proj), so a
    # converted tree works even where the config doesn't travel.
    attn_bias: bool = False
    # Rematerialisation policy when ``remat`` is on.  None = full-layer
    # recompute (lowest memory, ~1 extra forward of flops in the backward
    # — an MFU ceiling of ~0.75x hardware efficiency against the 6ND
    # count).  "dots" = save every no-batch-dim matmul output AND the
    # attention kernel's output (tagged "attn_out" in decoder_layer), so
    # the backward re-runs only the cheap elementwise chain (norms, rope,
    # silu) — the remat knob for MFU-bound training (BASELINE.md's
    # train_step_mfu >= 0.40 target) at O(S * D) extra saved bytes per
    # layer.
    remat_policy: Optional[str] = None
    # Layer iteration: True scans one compiled body over the stacked layer
    # tree (constant compile time at any depth); False unrolls a Python
    # loop over per-layer slices, which lets XLA schedule across layer
    # boundaries at the cost of depth-proportional compile time.  The
    # chunked "dots" remat is recompute-free under BOTH (pinned in
    # tests/test_remat_policy.py); scanned is the default and the
    # MFU-bench setting.
    scan_layers: bool = True
    # RoPE frequency scaling, as a hashable tuple (configs key jit caches):
    #   ("linear", factor)  — all frequencies divided by factor;
    #   ("llama3", factor, low_freq_factor, high_freq_factor,
    #    original_max_position_embeddings) — Llama-3.1's banded scheme
    #    (long wavelengths scaled, short kept, smooth band between).
    # None = unscaled.  Applied inside rope_tables via cfg_rope_tables.
    rope_scaling: Optional[tuple] = None

    def __post_init__(self):
        if self.sliding_window is not None and self.sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1, got {self.sliding_window}")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant must be 'none' or 'int8', got {self.kv_quant!r}")
        if self.head_dim_override is None:
            if self.d_model % self.n_heads:
                raise ValueError(
                    f"d_model={self.d_model} not divisible by "
                    f"n_heads={self.n_heads}; pass head_dim_override")
        elif self.head_dim_override < 2 or self.head_dim_override % 2:
            raise ValueError(f"head_dim_override must be an even int >= 2, "
                             f"got {self.head_dim_override}")
        if self.mlp_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"mlp_act must be 'silu' or 'gelu_tanh', got "
                f"{self.mlp_act!r}")
        if self.remat_policy not in (None, "dots"):
            raise ValueError(
                f"remat_policy must be None or 'dots', got "
                f"{self.remat_policy!r}")
        if self.remat_policy is not None and not self.remat:
            raise ValueError(
                "remat_policy is set but remat is False — the policy "
                "would be silently ignored; set remat=True")
        if self.rope_scaling is not None:
            s = tuple(self.rope_scaling)
            if not s or s[0] not in ("linear", "llama3", "yarn",
                                     "longrope", "longrope_fixed") or (
                    s[0] == "linear" and len(s) != 2) or (
                    s[0] == "llama3" and len(s) != 5) or (
                    s[0] == "yarn" and len(s) != 7) or (
                    s[0] == "longrope" and len(s) != 5) or (
                    s[0] == "longrope_fixed" and len(s) != 3):
                raise ValueError(
                    f"rope_scaling must be ('linear', factor), ('llama3', "
                    f"factor, low_freq_factor, high_freq_factor, "
                    f"original_max_position_embeddings), ('yarn', "
                    f"factor, original_max_position_embeddings, beta_fast, "
                    f"beta_slow, attention_factor, truncate), or "
                    f"('longrope', original_max_position_embeddings, "
                    f"attention_factor, short_factors, long_factors), got "
                    f"{self.rope_scaling!r}")
            if s[0] == "longrope":
                short, long = tuple(s[3]), tuple(s[4])
                half = self.head_dim // 2
                if len(short) != half or len(long) != half:
                    raise ValueError(
                        f"longrope factor lists must have head_dim//2="
                        f"{half} entries, got {len(short)}/{len(long)}")
                s = (s[0], s[1], s[2], short, long)
            elif s[0] == "longrope_fixed":
                ext = tuple(s[2])
                if len(ext) != self.head_dim // 2:
                    raise ValueError(
                        f"longrope_fixed factors must have head_dim//2="
                        f"{self.head_dim // 2} entries, got {len(ext)}")
                s = (s[0], s[1], ext)
            object.__setattr__(self, "rope_scaling", s)

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    PRESETS = {
        # BASELINE config 5 workload shape (Llama-3 8B).
        "llama3-8b": dict(vocab_size=128256, d_model=4096, n_layers=32,
                          n_heads=32, n_kv_heads=8, d_ff=14336,
                          rope_theta=500000.0),
        "llama2-7b": dict(vocab_size=32000, d_model=4096, n_layers=32,
                          n_heads=32, n_kv_heads=32, d_ff=11008,
                          rope_theta=10000.0),
        "debug": dict(vocab_size=512, d_model=128, n_layers=2, n_heads=8,
                      n_kv_heads=4, d_ff=256, dtype="float32"),
    }

    @classmethod
    def preset(cls, name: str, **overrides) -> "LlamaConfig":
        kw = dict(cls.PRESETS[name])
        kw.update(overrides)
        return cls(**kw)


# ------------------------------------------------------------------ params


def init_params(key, cfg: LlamaConfig) -> dict:
    """Stacked-layer parameter pytree.  Weights init: scaled normal."""
    dt = cfg.compute_dtype
    hd = cfg.head_dim
    keys = jax.random.split(key, 9)

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    Hq, Hkv = cfg.n_heads, cfg.n_kv_heads
    layers = {
        "wq": norm(keys[1], (L, D, Hq * hd), D**-0.5),
        "wk": norm(keys[2], (L, D, Hkv * hd), D**-0.5),
        "wv": norm(keys[3], (L, D, Hkv * hd), D**-0.5),
        "wo": norm(keys[4], (L, Hq * hd, D), (Hq * hd) ** -0.5),
        "attn_norm": jnp.ones((L, D), dt),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.attn_bias:
        layers.update(bq=jnp.zeros((L, Hq * hd), dt),
                      bk=jnp.zeros((L, Hkv * hd), dt),
                      bv=jnp.zeros((L, Hkv * hd), dt))
    if cfg.n_experts > 0:
        from .moe import init_moe_params

        layers["moe"] = init_moe_params(jax.random.fold_in(key, 17), L,
                                        cfg.n_experts, D, F, dt,
                                        swiglu=cfg.moe_swiglu)
    else:
        layers.update(
            w_gate=norm(keys[5], (L, D, F), D**-0.5),
            w_up=norm(keys[6], (L, D, F), D**-0.5),
            w_down=norm(keys[7], (L, F, D), F**-0.5),
        )
    return {
        "embed": norm(keys[0], (cfg.vocab_size, D), 0.02),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": norm(keys[8], (D, cfg.vocab_size), D**-0.5),
    }


def param_specs(cfg: LlamaConfig) -> dict:
    """GSPMD PartitionSpec tree: tensor-parallel over axis "tp".

    Projection out-dims (heads / ff) shard over tp; their consumers contract
    over the tp-sharded dim, so XLA inserts the reduce-scatter/all-reduce
    pattern over ICI automatically.  Embedding/lm_head shard the vocab dim.
    """
    layers = {
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
    }
    if cfg.attn_bias:
        # Biases live on the projection OUT dim: shard with their weight.
        layers.update(bq=P(None, "tp"), bk=P(None, "tp"), bv=P(None, "tp"))
    if cfg.n_experts > 0:
        from .moe import moe_specs

        layers["moe"] = moe_specs(swiglu=cfg.moe_swiglu)
    else:
        layers.update(
            w_gate=P(None, None, "tp"),
            w_up=P(None, None, "tp"),
            w_down=P(None, "tp", None),
        )
    return {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def quantized_param_specs(cfg: LlamaConfig) -> dict:
    """GSPMD PartitionSpec tree for a W8A16 tree
    (ops/quantize.py:quantize_params): each matmul leaf's raw spec
    applies to its ``q``, and its ``s`` (which drops the contracted
    axis, -2) keeps only the leading/output dims of that spec — so tp
    still shards the output channels and the scales follow them."""
    specs = param_specs(cfg)

    def split(spec):
        return {"q": spec, "s": P(*spec[:-2], spec[-1])}

    from ..ops.quantize import _MATMUL_LEAVES

    layers = dict(specs["layers"])
    for name in _MATMUL_LEAVES:
        if name in layers:
            layers[name] = split(layers[name])
    out = dict(specs)
    out["layers"] = layers
    out["lm_head"] = split(specs["lm_head"])
    return out


# ----------------------------------------------------------------- kernels


def matmul_w(x, w):
    """``x @ w`` where ``w`` is a raw array or a weight-quantized
    ``{"q": int8, "s": f32}`` pair (ops/quantize.py:quantize_params —
    the W8A16 serving tree).  Quantized weights stream at half width on
    TPU through the pallas gemv kernel (ops/pallas_gemv.py) with the
    per-output-channel scale folded into the product; elsewhere they
    dequantize-then-matmul.  Every matmul consumer of the parameter tree
    (decoder_layer, head_logits, the cached decode layer scan) routes
    through here, so ONE quantized tree serves
    forward/prefill/decode/serving/speculative alike."""
    if not (isinstance(w, dict) and "q" in w):
        return x @ w
    wq, s = w["q"], w["s"]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if jax.default_backend() == "tpu":
        from ..ops.pallas_gemv import int8_matmul

        out = int8_matmul(x2, wq, s)
    else:
        out = (x2.astype(jnp.float32)
               @ (wq.astype(jnp.float32) * s[None, :])).astype(x.dtype)
    return out.reshape(*lead, wq.shape[-1])


def rmsnorm(x, w, eps: float):
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def rope_tables(seq_len: int, head_dim: int, theta: float, scaling=None):
    """[S, Dh/2] cos/sin tables in f32.

    ``scaling``: LlamaConfig.rope_scaling tuple — ``("linear", factor)``
    divides every frequency by ``factor`` (position interpolation);
    ``("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)`` is Llama-3.1's banded scheme
    (public formula, as shipped in the checkpoints' reference code): long
    wavelengths (beyond ``orig/low``) scale by ``1/factor``, short ones
    (inside ``orig/high``) stay, and the band between interpolates
    smoothly in ``orig/wavelength``.  ``("yarn", factor,
    original_max_position_embeddings, beta_fast, beta_slow,
    attention_factor, truncate)`` is YaRN (NTK-by-parts, the public
    paper 2309.00071 formula as HF ships it; Qwen2.5-long /
    DeepSeek-family checkpoints): per-dimension blend of interpolated
    (``1/factor``) and unscaled frequencies along a linear ramp between
    the beta_fast/beta_slow correction dims, with ``attention_factor``
    (resolved at conversion, incl. the mscale variants) multiplying the
    cos/sin tables.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    att = 1.0
    if scaling is not None:
        kind = scaling[0]
        if kind == "linear":
            inv_freq = inv_freq / scaling[1]
        elif kind == "llama3":
            factor, low, high, orig = scaling[1:]
            wavelen = 2.0 * jnp.pi / inv_freq
            smooth = (orig / wavelen - low) / (high - low)
            mid = ((1.0 - smooth) / factor + smooth) * inv_freq
            inv_freq = jnp.where(
                wavelen > orig / low, inv_freq / factor,
                jnp.where(wavelen < orig / high, inv_freq, mid))
        elif kind == "yarn":
            import math

            factor, orig, beta_fast, beta_slow, att, truncate = scaling[1:]

            def corr_dim(rot):  # dimension rotating `rot` times over orig
                return (head_dim * math.log(orig / (rot * 2.0 * math.pi))
                        ) / (2.0 * math.log(theta))

            low, high = corr_dim(beta_fast), corr_dim(beta_slow)
            if truncate:
                low, high = math.floor(low), math.ceil(high)
            low, high = max(low, 0), min(high, head_dim - 1)
            if low == high:
                high += 0.001  # ramp singularity guard (HF-identical)
            ramp = jnp.clip(
                (jnp.arange(half, dtype=jnp.float32) - low) / (high - low),
                0.0, 1.0)
            extrap = 1.0 - ramp  # 1 where the dim extrapolates (short wl)
            inv_freq = (inv_freq / factor) * (1.0 - extrap) + inv_freq * extrap
        elif kind == "longrope":
            # LongRoPE (Phi-3.5/128k line; HF's longrope type): per-dim
            # rescale factors, the SHORT set within the original training
            # horizon and the LONG set beyond it — chosen by THIS table's
            # seq_len, matching HF's per-call `seq_len > orig` switch.
            # Multi-program runs (generate/serving build prefill AND
            # decode tables at different lengths) must NOT use this form
            # directly — mixed regimes within one run would silently
            # break the cached keys' rotation geometry; they resolve the
            # regime ONCE per run via resolve_longrope() below.
            orig, att, short, long = scaling[1:]
            ext = jnp.asarray(long if seq_len > orig else short,
                              jnp.float32)
            inv_freq = inv_freq / ext
        elif kind == "longrope_fixed":
            # Run-resolved longrope: one regime whatever this table's
            # length (produced by resolve_longrope).
            att, ext = scaling[1], jnp.asarray(scaling[2], jnp.float32)
            inv_freq = inv_freq / ext
        else:  # LlamaConfig.__post_init__ already validated
            raise ValueError(f"unknown rope scaling kind {kind!r}")
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    ang = pos[:, None] * inv_freq[None, :]
    return jnp.cos(ang) * att, jnp.sin(ang) * att


def cfg_rope_tables(cfg: "LlamaConfig", seq_len: int):
    """:func:`rope_tables` keyed entirely off a config — THE way model
    code builds tables (forgetting ``cfg.rope_scaling`` at one of the
    many call sites would silently mis-rotate positions)."""
    return rope_tables(seq_len, cfg.head_dim, cfg.rope_theta,
                       cfg.rope_scaling)


def resolve_longrope(cfg: "LlamaConfig", horizon: int) -> "LlamaConfig":
    """Pin a longrope config's factor regime to ``horizon`` (the run's
    max total length) for the WHOLE run.

    generate/serving/beam/speculative build prefill and decode tables at
    DIFFERENT seq_lens; the raw ("longrope", ...) form keys the
    short-vs-long choice off each table's own length, so a run with
    prompt <= orig < horizon would rotate cached keys and decode queries
    with different frequency sets — silently broken geometry.  This
    returns a config whose rope_scaling is ("longrope_fixed",
    attention_factor, ext_factors) chosen once by ``horizon``; every
    table in the run then agrees.  (HF switches regimes per step on
    horizon-crossing runs — a geometry-inconsistent quirk this design
    deliberately does not reproduce.)  Non-longrope configs pass
    through unchanged."""
    import dataclasses

    s = cfg.rope_scaling
    if s is None or s[0] != "longrope":
        return cfg
    orig, att, short, long = s[1:]
    ext = long if horizon > orig else short
    return dataclasses.replace(
        cfg, rope_scaling=("longrope_fixed", att, tuple(ext)))


def apply_rope(x, cos, sin):
    """x: [B, H, S, Dh]; split-half (NeoX) rotation convention: the two
    rotated components are x[..., :Dh/2] and x[..., Dh/2:].  ``cos``/``sin``
    are [S, Dh/2] tables, or already-broadcastable 4-D (e.g. per-row
    [B, 1, 1, Dh/2] angles for ragged decode).  NOTE: Meta's released Llama
    checkpoints use the interleaved-pair convention; loading them requires
    permuting wq/wk columns accordingly."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[None, None, :, :] if cos.ndim == 2 else cos
    s = sin[None, None, :, :] if sin.ndim == 2 else sin
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1).astype(x.dtype)


def head_logits(h, final_norm_w, lm_head_w, eps: float):
    """Model tail: final RMSNorm + lm_head, f32 logits.  Shared by the scan
    forward and the pipeline last stage (models/pp_llama.py)."""
    return matmul_w(rmsnorm(h, final_norm_w, eps), lm_head_w).astype(jnp.float32)


def token_ce(logits, targets):
    """Mean next-token cross-entropy of ``logits [..., V]`` against int ids
    ``targets [...]`` (same leading shape).

    Written as ``logsumexp - target_logit`` rather than gathering from a
    materialised ``log_softmax`` tensor: the ``[B, S, V]`` f32 logits are
    the biggest activation in a train step (1 GB at S=8192 V=32000), and
    the logp variant writes + re-reads a second one; here the reductions
    fuse into the logits' producer and only ``[B, S]`` scalars survive.
    Same math, same gradient (softmax - one_hot)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tl)


def _remat_wrap(layer, cfg: "LlamaConfig"):
    """Full-layer remat only.  The "dots" policy is NOT applied here: a
    jax.checkpoint policy that marks the q/k/v projection dots saveable
    around a pallas custom_vjp makes jax's partial-eval replay the flash
    forward kernel in the backward anyway (observed on jax 0.9; pinned in
    tests/test_remat_policy.py), so "dots" is implemented structurally
    inside :func:`decoder_layer` — two checkpointed chunks around an
    un-checkpointed attention call — rather than as a policy over the
    whole layer body."""
    if not cfg.remat:
        return layer
    if cfg.remat_policy == "dots":
        return layer  # chunked checkpointing lives inside decoder_layer
    return jax.checkpoint(layer)


def default_attn(q, k, v, window: Optional[int] = None):
    """Causal attention: the hand-tiled pallas kernel on TPU, the lax
    blockwise scan elsewhere (bit-compatible algebra, same GQA handling).
    ``window``: sliding-window causal — the flash kernel masks, skips, and
    DMA-elides out-of-window blocks in forward AND backward."""
    if jax.default_backend() == "tpu":
        from ..ops.pallas_attention import flash_attention

        return flash_attention(q, k, v, causal=True, interpret=False,
                               window=window)
    return blockwise_attention(q, k, v, causal=True, window=window)


def resolve_attn_fn(cfg: LlamaConfig, attn_fn: Optional[Callable]) -> Callable:
    """The one place attn_fn defaults and the sliding-window guard live
    (shared by the scan forward and models/pp_llama.py).

    None -> :func:`default_attn`, window-bound when the config has one.  A
    supplied attn_fn on a windowed config must declare
    ``attn_fn.handles_window = True`` — silently training/serving
    full-causal on a windowed config is a different model.
    :func:`make_sharded_attn` (plain ring layout; band-skipped steps)
    and :func:`~starway_tpu.parallel.ulysses.make_ulysses_attention`
    declare it when built with ``window=``; zigzag doesn't implement
    windows.
    """
    if attn_fn is None:
        if cfg.sliding_window is not None:
            return partial(default_attn, window=cfg.sliding_window)
        return default_attn
    if cfg.sliding_window is not None:
        if not getattr(attn_fn, "handles_window", False):
            raise ValueError(
                "cfg.sliding_window is set but the supplied attn_fn does "
                "not declare window support (attn_fn.handles_window)")
        declared = getattr(attn_fn, "window", None)
        if declared is not None and declared != cfg.sliding_window:
            # A mismatched band is silently a different model — the exact
            # failure this guard exists to prevent.
            raise ValueError(
                f"attn_fn was built with window={declared} but "
                f"cfg.sliding_window={cfg.sliding_window}")
    return attn_fn


# ----------------------------------------------------------------- forward


def embed_tokens(params: dict, tokens, cfg: "LlamaConfig"):
    """Token embedding gather, with Gemma's sqrt(d_model) output scaling
    when ``cfg.scaled_embed`` — the ONE embed site every entry point
    (forward/prefill, decode_step, chunk_decode_step, the pipeline step)
    shares, so no path can forget the normalizer."""
    h = params["embed"][tokens]
    if cfg.scaled_embed:
        # HF Gemma multiplies by a normalizer tensor cast to model dtype.
        h = h * jnp.asarray(cfg.d_model ** 0.5, h.dtype)
    return h


def mlp_gate_act(x, cfg: "LlamaConfig"):
    """The gated-MLP nonlinearity in f32 (MXU outputs accumulate f32):
    SiLU (Llama) or tanh-approximated GeLU (Gemma's GeGLU)."""
    xf = x.astype(jnp.float32)
    if cfg.mlp_act == "gelu_tanh":
        return jax.nn.gelu(xf, approximate=True)
    return jax.nn.silu(xf)


def qkv_proj(x, lp, cfg: "LlamaConfig"):
    """q/k/v projections on ``x [B, S, D]`` -> ``[B, H, S, hd]`` heads,
    pre-RoPE.  Optional per-head biases (Qwen2 family) apply when the
    layer tree carries ``bq``/``bk``/``bv`` — leaf presence is the
    marker, so converted trees work wherever the config doesn't travel.
    The ONE projection site shared by the scan forward (decoder_layer)
    and the cached decode layer scan (generate.py)."""
    B, S = x.shape[0], x.shape[1]
    hd = cfg.head_dim
    q = matmul_w(x, lp["wq"])
    k = matmul_w(x, lp["wk"])
    v = matmul_w(x, lp["wv"])
    if "bq" in lp:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    return (q.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3),
            k.reshape(B, S, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3),
            v.reshape(B, S, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3))


def decoder_layer(lp, h, cfg: LlamaConfig, cos, sin,
                  attn_fn: Callable, moe_fn: Optional[Callable] = None):
    """One pre-norm decoder block on ``h [B, S, D]`` with layer params
    ``lp`` (one slice of the stacked tree).  Returns
    ``(h, aux, k, v, stats)`` — aux is the MoE balance term (0 for dense),
    k/v the post-RoPE grouped heads (the KV-cache prefix), stats the MoE
    router-health dict when ``moe_fn`` returns one (``with_stats=True``
    builders), else None.  Shared by the scan forward, and the
    pipeline-parallel stage body (models/pp_llama.py)."""
    B, S, _ = h.shape
    hd = cfg.head_dim
    # "dots" remat is CHUNKED: two checkpointed regions around an
    # un-checkpointed attention call.  A whole-layer jax.checkpoint with a
    # dots-saveable policy silently replays the flash forward kernel in the
    # backward (jax 0.9 partial-eval; pinned in tests/test_remat_policy.py),
    # while this structure provably does not: the pre chunk's saved
    # boundary IS (q, k, v), the attention custom_vjp's residuals
    # (q, k, v, o, lse) ride the scan as usual, and the post chunk
    # name-saves only the gate/up dots — the backward replays nothing but
    # norms, rope, and silu.
    chunked = cfg.remat and cfg.remat_policy == "dots"

    def pre(h, lp):
        x = rmsnorm(h, lp["attn_norm"], cfg.norm_eps)
        q, k, v = qkv_proj(x, lp, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # kv stays in grouped (narrow) form; attention impls expand it, so
        # the ring rotates 1/n_rep of the bytes over ICI.
        return q, k, v

    def post(h, o, lp):
        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.n_heads * hd)
        h = h + matmul_w(o, lp["wo"])

        x = rmsnorm(h, lp["mlp_norm"], cfg.norm_eps)
        stats = None
        if cfg.n_experts > 0:
            if moe_fn is not None:
                # SwiGLU expert trees carry w_gate; pass it only when
                # present so 4-arg moe_fns (Switch-style) keep working.
                kw = ({"w_gate": lp["moe"]["w_gate"]}
                      if "w_gate" in lp["moe"] else {})
                out = moe_fn(
                    x, lp["moe"]["router"], lp["moe"]["w_in"],
                    lp["moe"]["w_out"], **kw)
                y, aux = out[0], out[1]
                if len(out) > 2:  # with_stats moe_fn: router-health metrics
                    stats = out[2]
            else:
                from .moe import switch_moe

                y, aux = switch_moe(
                    x, lp["moe"]["router"], lp["moe"]["w_in"],
                    lp["moe"]["w_out"],
                    capacity_factor=cfg.moe_capacity_factor,
                    k=cfg.moe_top_k, w_gate=lp["moe"].get("w_gate"),
                )
            h = h + y
        else:
            g = checkpoint_name(matmul_w(x, lp["w_gate"]), "mlp_gate")
            u = checkpoint_name(matmul_w(x, lp["w_up"]), "mlp_up")
            gate = mlp_gate_act(g, cfg).astype(x.dtype)
            h = h + matmul_w(gate * u, lp["w_down"])
            aux = jnp.zeros((), jnp.float32)
        return h, aux, stats

    if chunked:
        # pre: boundary outputs (q, k, v) are saved by construction; the
        # backward replays only rmsnorm + rope (the projection dot outputs
        # are not themselves backward inputs).  post: gate/up dots saved
        # by name (silu's vjp and dW_down need them); every other matmul
        # output in the chunk is not a backward input, so the replay is
        # elementwise.  No pallas call sits inside either region, so the
        # policy pathology above cannot trigger.  MoE layers keep their
        # dispatch collectives inside post — replayed in the backward,
        # matching the pre-chunking "dots" behavior — while expert dot
        # outputs are saved via dots_with_no_batch_dims.
        pre = jax.checkpoint(pre)
        post = jax.checkpoint(
            post,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "mlp_gate", "mlp_up")))

    q, k, v = pre(h, lp)
    o = attn_fn(q, k, v)  # [B, H, S, Dh]
    # Tag kept for user-supplied whole-model remat policies; the flash
    # kernel additionally tags o and lse internally (pallas_attention).
    o = checkpoint_name(o, "attn_out")
    h, aux, stats = post(h, o, lp)
    return h, aux, k, v, stats


def forward(params: dict, tokens, cfg: LlamaConfig,
            attn_fn: Optional[Callable] = None, *, return_aux: bool = False,
            moe_fn: Optional[Callable] = None, return_kv: bool = False,
            last_only: bool = False, logit_positions=None,
            return_moe_stats: bool = False):
    """Next-token logits ``[B, S, V]`` for token ids ``[B, S]``.

    ``return_kv`` additionally returns the post-RoPE grouped k/v of every
    layer, scan-stacked ``[n_layers, B, Hkv, S, Dh]`` -- the KV-cache prefix
    for :func:`~starway_tpu.models.generate.prefill` (one flash-attention
    pass over the whole prompt instead of S cached decode steps).
    ``last_only`` applies the final norm + lm_head to the last position only
    (``[B, 1, V]``), skipping the ``[B, S, V]`` logit tensor a prefill never
    reads; ``logit_positions`` ([B] ints) is its ragged analog — logits for
    one caller-chosen position per row.  Return value is ``logits``,
    extended to a tuple ``(logits[, aux][, moe_stats][, (k, v)])`` by
    ``return_aux`` / ``return_moe_stats`` / ``return_kv``.

    ``attn_fn(q, k, v) -> out`` takes q ``[B, Hq, S, Dh]`` and *grouped*
    kv ``[B, Hkv, S, Dh]`` (impls expand GQA heads internally); defaults to
    single-device blockwise attention.  Pass :func:`make_sharded_attn`'s
    result for sequence-parallel ring attention.

    ``moe_fn(x, router_w, w_in, w_out) -> (y, aux)`` overrides the MoE FFN
    when ``cfg.n_experts > 0``; defaults to the global-view
    :func:`~starway_tpu.models.moe.switch_moe` (GSPMD dispatch).  Pass
    :func:`~starway_tpu.models.moe.make_sharded_moe`'s result to pin the
    expert all-to-all over the "ep" mesh axis explicitly — built with
    ``with_stats=True`` plus ``return_moe_stats=True`` here, the
    layer-stacked router-health dict (drop fraction, per-expert load; each
    leaf gains a leading ``n_layers`` dim) is appended to the outputs.
    """
    attn_fn = resolve_attn_fn(cfg, attn_fn)
    if return_moe_stats and cfg.n_experts == 0:
        raise ValueError(
            "return_moe_stats=True but cfg.n_experts == 0: a dense model "
            "has no router to report on")
    if return_moe_stats and moe_fn is None:
        raise ValueError(
            "return_moe_stats needs a stats-producing moe_fn (build one "
            "with make_sharded_moe(..., with_stats=True) or wrap "
            "switch_moe(..., with_stats=True))")
    B, S = tokens.shape
    cos, sin = cfg_rope_tables(cfg, S)

    h = embed_tokens(params, tokens, cfg)  # [B, S, D]

    def layer(carry, lp):
        h, aux = carry
        h, layer_aux, k, v, stats = decoder_layer(lp, h, cfg, cos, sin,
                                                  attn_fn, moe_fn=moe_fn)
        if return_moe_stats and stats is None:
            raise ValueError("return_moe_stats=True but moe_fn returned no "
                             "stats (build it with with_stats=True)")
        return (h, aux + layer_aux), ((k, v) if return_kv else None,
                                      stats if return_moe_stats else None)

    body = _remat_wrap(layer, cfg)
    if cfg.scan_layers:
        (h, aux), (kv, moe_stats) = lax.scan(
            body, (h, jnp.zeros((), jnp.float32)), params["layers"])
    else:
        # Unrolled: same body, Python loop over layer slices; per-layer
        # outputs are stacked to match the scan's [n_layers, ...] layout.
        carry = (h, jnp.zeros((), jnp.float32))
        ys = []
        for i in range(cfg.n_layers):
            lp = jax.tree_util.tree_map(lambda x: x[i], params["layers"])
            carry, y = body(carry, lp)
            ys.append(y)
        h, aux = carry
        kv, moe_stats = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *ys)
    if last_only:
        h = h[:, -1:]
    elif logit_positions is not None:
        h = jnp.take_along_axis(h, logit_positions[:, None, None], axis=1)
    logits = head_logits(h, params["final_norm"], params["lm_head"], cfg.norm_eps)
    out = (logits,)
    if return_aux:
        out += (aux,)
    if return_moe_stats:
        out += (moe_stats,)  # scan-stacked: leaves lead with n_layers
    if return_kv:
        out += (kv,)
    return out if len(out) > 1 else logits


def loss_fn(params: dict, batch, cfg: LlamaConfig,
            attn_fn: Optional[Callable] = None,
            moe_fn: Optional[Callable] = None, *,
            with_moe_stats: bool = False):
    """Causal LM loss: batch ``[B, S+1]`` token ids -> mean next-token
    cross-entropy.  ``with_moe_stats``: return ``(loss, stats)`` (for
    ``jax.value_and_grad(..., has_aux=True)``) with the layer-stacked MoE
    router-health dict — requires a ``with_stats=True`` moe_fn."""
    tokens, targets = batch[:, :-1], batch[:, 1:]
    if with_moe_stats:
        logits, aux, stats = forward(params, tokens, cfg, attn_fn,
                                     return_aux=True, moe_fn=moe_fn,
                                     return_moe_stats=True)
    else:
        logits, aux = forward(params, tokens, cfg, attn_fn, return_aux=True,
                              moe_fn=moe_fn)
    loss = token_ce(logits, targets)
    if cfg.n_experts > 0:
        loss = loss + cfg.moe_aux_coef * aux / cfg.n_layers
    return (loss, stats) if with_moe_stats else loss


def apply_updates(tx, params, opt_state, grads):
    """Optimizer transform + parameter update, shared by make_train_step and
    the Trainer's standalone apply step (keeps the two jitted paths
    identical)."""
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)), params, updates
    )
    return params, opt_state


def make_train_step(cfg: LlamaConfig, tx, attn_fn: Optional[Callable] = None,
                    moe_fn: Optional[Callable] = None, *,
                    accum_steps: int = 1, with_moe_stats: bool = False):
    """One optimizer step, jit-ready (donate params+opt_state for in-place
    HBM updates).

    ``accum_steps > 1`` splits the batch into that many equal microbatches
    and accumulates gradients in f32 across a ``lax.scan`` before the
    single optimizer update — activation memory scales with the microbatch
    while the math matches the full-batch step for dense models
    (equal-size chunks make the mean of means the global mean; pinned by
    tests/test_model.py).  MoE models still train correctly but are not
    bit-identical to the full-batch step: expert capacity is computed per
    microbatch, so routing overflow can differ.

    ``with_moe_stats`` (needs a ``with_stats=True`` moe_fn): the step
    returns ``(params, opt_state, loss, stats)`` where stats is the
    layer-stacked router-health dict (drop fraction + per-expert load,
    leading ``n_layers`` dim; averaged over microbatches under accum) —
    the training loop sees a collapsing router instead of silent drops.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def value_and_grad(params, batch):
        if with_moe_stats:
            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, cfg, attn_fn, moe_fn, with_moe_stats=True)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, cfg, attn_fn, moe_fn)
            stats = None
        return loss, grads, stats

    def train_step(params, opt_state, batch):
        if accum_steps == 1:
            loss, grads, stats = value_and_grad(params, batch)
        else:
            B = batch.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"batch {B} not divisible by accum_steps={accum_steps}")
            chunks = batch.reshape(accum_steps, B // accum_steps,
                                   *batch.shape[1:])

            def acc(carry, chunk):
                loss_sum, gacc = carry
                l, g, stats = value_and_grad(params, chunk)
                gacc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32), gacc, g)
                return (loss_sum + l, gacc), stats

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_sum, grads), stats = lax.scan(
                acc, (jnp.float32(0), zeros), chunks)
            if with_moe_stats:  # mean over the microbatch chunks
                stats = jax.tree_util.tree_map(
                    lambda s: jnp.mean(s, axis=0), stats)
            loss = loss_sum / accum_steps
            # Back to param dtype: the optimizer must see the same grad
            # dtype as the accum_steps=1 path, else bf16 adamw moments get
            # promoted to f32 on step 1 (donation breaks + a recompile).
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / accum_steps).astype(p.dtype), grads, params)
        params, opt_state = apply_updates(tx, params, opt_state, grads)
        if with_moe_stats:
            return params, opt_state, loss, stats
        return params, opt_state, loss

    return train_step


def make_sharded_attn(mesh, *, seq_axis: str = "sp", dp_axis: str = "dp",
                      tp_axis: str = "tp", layout: str = "ring",
                      window: Optional[int] = None):
    """Sequence-parallel ring attention for use as ``attn_fn`` inside the
    GSPMD-jitted forward: q/k/v arrive [B, H, S, Dh] with batch sharded over
    dp, heads over tp, sequence over sp; the (grouped, narrow) kv shards
    ride the ICI ring.  Requires n_kv_heads % tp == 0.

    ``layout="zigzag"`` uses the load-balanced causal layout
    (parallel/ring_attention.py:zigzag_indices): ~2x causal wall-clock at
    long S because no device spends ring steps on fully-masked blocks, at
    the cost of a sequence permutation (an sp-axis shuffle) per call --
    worth it when S is large enough that attention compute dominates.

    ``window``: sliding-window band (match ``cfg.sliding_window``; the
    returned fn declares ``handles_window`` so resolve_attn_fn admits it
    on windowed configs).  Ring layout only — out-of-band ring steps
    cond-skip their compute, so wall-clock scales with the band.
    """
    from ..parallel.ring_attention import (
        ring_attention,
        zigzag_ring_attention,
        zigzag_wrap,
    )
    from ..parallel.sharding import shard_map_fn

    if layout not in ("ring", "zigzag"):
        raise ValueError(f"unknown attention layout {layout!r}; expected 'ring' or 'zigzag'")
    if window is not None and layout != "ring":
        raise ValueError(
            "window is supported on the plain ring layout only (zigzag's "
            "interleaved shards break the contiguous band-skip argument)")

    spec = P(dp_axis, tp_axis, seq_axis, None)

    if layout == "zigzag":
        def local_z(q, k, v):
            return zigzag_ring_attention(q, k, v, seq_axis)

        inner = shard_map_fn(mesh, local_z, in_specs=(spec, spec, spec), out_specs=spec)
        return zigzag_wrap(inner, mesh.shape[seq_axis])

    def local(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=True, window=window)

    fn = shard_map_fn(mesh, local, in_specs=(spec, spec, spec),
                      out_specs=spec)
    if window is not None:
        fn.handles_window = True
        fn.window = window  # resolve_attn_fn cross-checks vs the config
    return fn
