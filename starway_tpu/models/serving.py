"""Continuous-batching serving: admit requests into a RUNNING batch.

``generate()`` (models/generate.py) serves one static batch per dispatch —
every row starts together and the dispatch lasts the full generation.  A
real serving workload is a stream: requests arrive at any time, finish at
different lengths, and a finished row's slot should start the next request
immediately instead of idling until the batch drains (the continuous-
batching idea of Orca/vLLM, built TPU-first here).

Design for XLA's compilation model — everything the device runs is one of
a FIXED, small set of compiled programs:

* **Slots, not batches.**  The KV cache is ``[L, n_slots, Hkv, max_len,
  Dh]``; every per-slot cursor (position, liveness, token budget) is a
  ``[n_slots]`` vector.  Shapes never depend on which requests are in
  flight.
* **Admission = bucketed prefill.**  A new request's prompt is right-padded
  to a power-of-two bucket and prefilled in its own dispatch (one compile
  per bucket), then its kv rows are written into the slot with a dynamic
  slice.  Pad/garbage columns are never read: attention masks by the
  slot's cursor, and decode overwrites each position before the cursor
  reaches it (write-then-attend).
* **Decode runs in chunks.**  One compiled ``lax.scan`` advances ALL live
  slots ``chunk`` tokens (dead slots are masked: frozen cursor, writes
  land on a position that admission or the advancing cursor overwrites
  before any read).  Per-token host round-trips — fatal on a tunneled
  device — happen once per chunk, not once per token.
* **Greedy continuous batching is BIT-IDENTICAL to standalone
  ``generate()``** for every request, whatever the interleaving: same
  prefill, same decode step, same masking — pinned by
  tests/test_serving.py against the one-request oracle.

Sliding-window (Mistral-family) models serve through per-slot ROLLING
caches: O(window) memory per slot however long each generation runs,
admission via the chunked ``prefill_rolling`` (no prompt bucketing — its
compiled chunk body is length-independent), and ``max_len`` bounding only
the rope horizon.  Dense models only (MoE expert capacity is shared
batch-wide, so slot cohabitation would perturb routing — same restriction
as ragged ``generate()``).
"""

from __future__ import annotations

import functools
from collections import deque
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .generate import (_sample, decode_step, init_cache, init_rolling_cache,
                       prefill)
from .llama import LlamaConfig, cfg_rope_tables


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket "
                     f"{buckets[-1]}")


def _write_slot_and_sample(cache, small, logits, slot, key, temperature,
                           top_k, top_p):
    """Shared tail of BOTH admission paths: file one request's [L, 1, Hkv,
    T', D] cache rows into the slot and sample its first token.  Writes
    every cache leaf — the int8 format's [L, 1, Hkv, T'] scale arrays ride
    along (the slot axis sits at index 1 in all of them)."""
    cache = {
        name: lax.dynamic_update_slice(
            cache[name], small[name], (0, slot) + (0,) * (cache[name].ndim - 2))
        for name in cache
    }
    tok = _sample(logits, key, temperature, top_k, top_p)[0]
    return cache, tok


@functools.cache
def _compiled_admit(cfg: LlamaConfig, p_bucket: int, temperature: float,
                    top_k: Optional[int], top_p: Optional[float]):
    """Prefill one request into one slot: returns the updated cache and the
    request's FIRST generated token.  One compile per prompt bucket."""

    def run(params, cache, prompt, length, slot, key):
        # prompt [1, p_bucket] right-padded; ragged single-row prefill.
        # Columns >= length hold pad-garbage that is overwritten (position
        # by position) before the cursor lets attention read it.
        logits, small = prefill(params, cfg, prompt, p_bucket,
                                logit_positions=length[None] - 1)
        return _write_slot_and_sample(cache, small, logits, slot, key,
                                      temperature, top_k, top_p)

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_rolling_admit(cfg: LlamaConfig, temperature: float,
                            top_k: Optional[int], top_p: Optional[float]):
    """Rolling-cache admission, final part: write the request's [L, 1,
    Hkv, W, D] rolling cache into the slot and sample the first token."""

    def run(cache, small, logits, slot, key):
        return _write_slot_and_sample(cache, small, logits, slot, key,
                                      temperature, top_k, top_p)

    return jax.jit(run, donate_argnums=(0,))


# Chunk-width denominations for rolling admission: covering the prompt
# greedily with these bounds admission to <= 3 compiled chunk programs per
# config and <= P/64 + 7 + 7 dispatches — arbitrary prompt lengths never
# trigger fresh XLA compiles mid-serve (the compile explosion prompt
# bucketing prevents on the dense path).
ROLLING_ADMIT_WIDTHS = (64, 8, 1)


def _rolling_prefill_state(params, cfg: LlamaConfig, prompt: np.ndarray):
    """(next_logits [1, V], rolling cache [L, 1, Hkv, W, D]) for one
    prompt via denomination-scheduled ``prefill_rolling``.  Shared by
    admission and the serving tests' single-request oracle."""
    from .generate import prefill_rolling

    return prefill_rolling(params, cfg, jnp.asarray(prompt[None], jnp.int32),
                           widths=ROLLING_ADMIT_WIDTHS)


@functools.cache
def _compiled_chunk(cfg: LlamaConfig, n_slots: int, max_len: int, chunk: int,
                    temperature: float, top_k: Optional[int],
                    top_p: Optional[float], eos_id: Optional[int],
                    rolling: bool = False):
    """Advance every live slot ``chunk`` tokens in ONE dispatch.

    Per step: the pending token (at its slot's cursor) runs
    ``decode_step`` with per-row positions, the next token is sampled,
    budgets/eos update liveness.  Emits ``(tokens [chunk, B], mask
    [chunk, B])`` — mask marks which emissions are real (slot was live
    when its PENDING token was consumed, i.e. the sampled token continues
    a real request).  ``rolling``: the cache is circular per slot
    (``max_len`` is the rope horizon, not the cache size).
    """
    rope = cfg_rope_tables(cfg, max_len)

    def run(params, cache, token, pos, live, remaining, key):
        def step(carry, _):
            cache, token, pos, live, remaining, key = carry
            logits, cache = decode_step(params, cache, token, pos, cfg, rope,
                                        rolling=rolling)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, temperature, top_k, top_p)
            emit_live = live & (remaining > 0)
            if eos_id is not None:
                newly_done = emit_live & (nxt == eos_id)
            else:
                newly_done = jnp.zeros_like(emit_live)
            remaining = remaining - emit_live.astype(jnp.int32)
            live = emit_live & ~newly_done & (remaining > 0) & (
                pos + 2 < max_len)
            # Dead slots freeze: cursor stays, pending token irrelevant
            # (their cache writes land on a position admission or the
            # cursor overwrites before any read).
            pos = pos + emit_live.astype(jnp.int32)
            token = jnp.where(emit_live, nxt, token)
            return (cache, token, pos, live, remaining, key), (nxt, emit_live)

        (cache, token, pos, live, remaining, key), (toks, mask) = lax.scan(
            step, (cache, token, pos, live, remaining, key), None,
            length=chunk)
        return cache, token, pos, live, remaining, key, toks, mask

    return jax.jit(run, donate_argnums=(1,))


class SlotServer:
    """Continuous-batching front end over the compiled admit/decode programs.

    >>> srv = SlotServer(params, cfg, n_slots=4, max_len=512)
    >>> rid = srv.submit([1, 2, 3], max_new_tokens=32)
    >>> done = srv.run()          # {rid: np.ndarray of generated tokens}

    ``submit`` queues; ``step()`` admits pending requests into free slots
    and advances one decode chunk, returning newly finished requests;
    ``run()`` loops until everything queued has finished.  Generated
    tokens INCLUDE the terminating eos (when ``eos_id`` fires).
    """

    def __init__(self, params, cfg: LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, chunk: int = 8,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, eos_id: Optional[int] = None,
                 prompt_buckets=None, seed: int = 0):
        if cfg.n_experts > 0:
            raise ValueError(
                "continuous batching is dense-only: MoE expert capacity is "
                "shared batch-wide, so cohabiting slots would perturb each "
                "other's routing (same restriction as ragged generate())")
        self.rolling = cfg.sliding_window is not None
        if n_slots < 1 or chunk < 1:
            # Zero slots/chunk would make run() spin forever, not error.
            raise ValueError(f"need n_slots >= 1 and chunk >= 1, got "
                             f"{n_slots}/{chunk}")
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.chunk = chunk
        self.sampling = (float(temperature), top_k, top_p)
        self.eos_id = None if eos_id is None else int(eos_id)
        if self.rolling:
            self.buckets = ()  # rolling admission never buckets prompts
        else:
            if prompt_buckets is None:
                b, buckets = 32, []
                while b < max_len:
                    buckets.append(b)
                    b *= 2
                # Always cover the full cache: a prompt up to max_len - 1
                # must have a bucket, or submit-accepted requests would
                # die at admission time.
                buckets.append(max_len)
                prompt_buckets = tuple(buckets)
            self.buckets = tuple(sorted(set(prompt_buckets)))
            if self.buckets[-1] > max_len:
                raise ValueError(f"bucket {self.buckets[-1]} exceeds "
                                 f"max_len={max_len}")
        self.key = jax.random.PRNGKey(seed)

        # Rolling (sliding-window) models keep an O(window) circular cache
        # per slot; max_len then bounds the ROPE horizon (prompt + budget),
        # not cache memory.
        self.cache = (init_rolling_cache(cfg, n_slots) if self.rolling
                      else init_cache(cfg, n_slots, max_len))
        self.token = jnp.zeros((n_slots,), jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.live = jnp.zeros((n_slots,), bool)
        self.remaining = jnp.zeros((n_slots,), jnp.int32)

        self._next_rid = 0
        self._pending: deque = deque()
        self._slot_rid: dict[int, int] = {}
        self._collected: dict[int, list] = {}

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens: int) -> int:
        """Queue one request; returns its id (resolved by step()/run())."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        if not self.rolling:
            _bucket(len(prompt), self.buckets)  # reject un-bucketable NOW,
            # not at admission time after the request has left the queue
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append((rid, prompt, int(max_new_tokens)))
        return rid

    # ------------------------------------------------------------- engine
    def _admit(self, slot: int, rid: int, prompt: np.ndarray,
               max_new: int) -> None:
        self.key, sub = jax.random.split(self.key)
        if self.rolling:
            # Chunked O(window) prefill with denomination widths: at most
            # len(ROLLING_ADMIT_WIDTHS) compiled programs, any prompt
            # length.
            logits, small = _rolling_prefill_state(
                self.params, self.cfg, prompt)
            admit = _compiled_rolling_admit(self.cfg, *self.sampling)
            self.cache, tok = admit(self.cache, small, logits,
                                    jnp.asarray(slot, jnp.int32), sub)
        else:
            pb = _bucket(len(prompt), self.buckets)
            padded = np.zeros((1, pb), np.int32)
            padded[0, :len(prompt)] = prompt
            admit = _compiled_admit(self.cfg, pb, *self.sampling)
            self.cache, tok = admit(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(len(prompt), jnp.int32),
                jnp.asarray(slot, jnp.int32), sub)
        tok_host = int(tok)
        self._slot_rid[slot] = rid
        self._collected[rid] = [tok_host]
        done = (max_new == 1 or
                (self.eos_id is not None and tok_host == self.eos_id))
        self.token = self.token.at[slot].set(tok_host)
        self.pos = self.pos.at[slot].set(len(prompt))
        self.live = self.live.at[slot].set(not done)
        self.remaining = self.remaining.at[slot].set(max_new - 1)

    def _harvest_dead(self, finished: dict) -> None:
        live = np.asarray(self.live)
        for slot, rid in list(self._slot_rid.items()):
            if not live[slot]:
                finished[rid] = np.asarray(self._collected.pop(rid),
                                           np.int32)
                del self._slot_rid[slot]

    def step(self) -> dict:
        """Admit what fits, decode one chunk; returns {rid: tokens} for
        requests that finished during this step."""
        finished: dict = {}
        self._harvest_dead(finished)  # 1-token/instant-eos admissions
        free = [s for s in range(self.n_slots) if s not in self._slot_rid]
        while free and self._pending:
            rid, prompt, max_new = self._pending.popleft()
            self._admit(free.pop(0), rid, prompt, max_new)
        self._harvest_dead(finished)
        if not self._slot_rid:
            return finished

        run = _compiled_chunk(self.cfg, self.n_slots, self.max_len,
                              self.chunk, *self.sampling, self.eos_id,
                              rolling=self.rolling)
        self.key, sub = jax.random.split(self.key)
        (self.cache, self.token, self.pos, self.live, self.remaining,
         _key, toks, mask) = run(self.params, self.cache, self.token,
                                 self.pos, self.live, self.remaining, sub)
        toks = np.asarray(toks)
        mask = np.asarray(mask)
        for slot, rid in self._slot_rid.items():
            self._collected[rid].extend(
                int(t) for t, m in zip(toks[:, slot], mask[:, slot]) if m)
        self._harvest_dead(finished)
        return finished

    def run(self) -> dict:
        """Drive step() until every submitted request has finished."""
        finished: dict = {}
        while self._pending or self._slot_rid:
            finished.update(self.step())
        return finished
