"""Continuous-batching serving: admit requests into a RUNNING batch.

``generate()`` (models/generate.py) serves one static batch per dispatch —
every row starts together and the dispatch lasts the full generation.  A
real serving workload is a stream: requests arrive at any time, finish at
different lengths, and a finished row's slot should start the next request
immediately instead of idling until the batch drains (the continuous-
batching idea of Orca/vLLM, built TPU-first here).

Design for XLA's compilation model — everything the device runs is one of
a FIXED, small set of compiled programs:

* **Slots, not batches.**  The KV cache is ``[L, n_slots, Hkv, max_len,
  Dh]``; every per-slot cursor (position, liveness, token budget) is a
  ``[n_slots]`` vector.  Shapes never depend on which requests are in
  flight.
* **Admission = bucketed prefill.**  A new request's prompt is right-padded
  to a power-of-two bucket and prefilled in its own dispatch (one compile
  per bucket), then its kv rows are written into the slot with a dynamic
  slice.  Pad/garbage columns are never read: attention masks by the
  slot's cursor, and decode overwrites each position before the cursor
  reaches it (write-then-attend).
* **Decode runs in chunks.**  One compiled ``lax.scan`` advances ALL live
  slots ``chunk`` tokens (dead slots are masked: frozen cursor, writes
  land on a position that admission or the advancing cursor overwrites
  before any read).  Per-token host round-trips — fatal on a tunneled
  device — happen once per chunk, not once per token.
* **Greedy continuous batching is BIT-IDENTICAL to standalone
  ``generate()``** for every request, whatever the interleaving: same
  prefill, same decode step, same masking — pinned by
  tests/test_serving.py against the one-request oracle.

* **Prefix caching.**  ``register_prefix`` prefills a shared prefix once
  into a standalone [L, 1, Hkv, bucket, Dh] cache; prefixed admission
  copies those rows into the slot masked by position (< plen — bucket
  junk above the prefix must not land where suffix positions would
  attend it) and ingests the suffix through ONE
  ``chunk_decode_step`` forward against the slot's own rows
  (write-then-attend, the decode-path semantics) — so a prefixed request
  generates exactly what ``generate(prefix + suffix)`` would, while
  admission compute scales with the suffix.  One compile per
  (prefix bucket, suffix bucket).

Sliding-window (Mistral-family) models serve through per-slot ROLLING
caches: O(window) memory per slot however long each generation runs,
admission via the chunked ``prefill_rolling`` (no prompt bucketing — its
compiled chunk body is length-independent), and ``max_len`` bounding only
the rope horizon.  MoE models serve when capacity is provably dropless
(``moe_capacity_factor >= n_experts``): expert capacity is shared
batch-wide, so slot cohabitation could otherwise perturb routing — the
same rule as ragged ``generate()``.
"""

from __future__ import annotations

import functools
from collections import deque
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .generate import (_sample, decode_step, init_cache, init_rolling_cache,
                       prefill)
from .llama import LlamaConfig, cfg_rope_tables


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket "
                     f"{buckets[-1]}")


def _write_slot_and_sample(cache, small, logits, slot, key, temperature,
                           top_k, top_p):
    """Shared tail of BOTH admission paths: file one request's [L, 1, Hkv,
    T', D] cache rows into the slot and sample its first token.  Writes
    every cache leaf — the int8 format's [L, 1, Hkv, T'] scale arrays ride
    along (the slot axis sits at index 1 in all of them)."""
    cache = {
        name: lax.dynamic_update_slice(
            cache[name], small[name], (0, slot) + (0,) * (cache[name].ndim - 2))
        for name in cache
    }
    tok = _sample(logits, key, temperature, top_k, top_p)[0]
    return cache, tok


@functools.cache
def _compiled_admit(cfg: LlamaConfig, p_bucket: int, temperature: float,
                    top_k: Optional[int], top_p: Optional[float]):
    """Prefill one request into one slot: returns the updated cache and the
    request's FIRST generated token.  One compile per prompt bucket."""

    def run(params, cache, prompt, length, slot, key):
        # prompt [1, p_bucket] right-padded; ragged single-row prefill.
        # Columns >= length hold pad-garbage that is overwritten (position
        # by position) before the cursor lets attention read it.
        logits, small = prefill(params, cfg, prompt, p_bucket,
                                logit_positions=length[None] - 1)
        return _write_slot_and_sample(cache, small, logits, slot, key,
                                      temperature, top_k, top_p)

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_prefix_register(cfg: LlamaConfig, p_bucket: int):
    """Prefill one PREFIX into a standalone [L, 1, Hkv, p_bucket, D] cache
    (plus its next-token logits, so a zero-length suffix could continue).
    One compile per prefix bucket."""

    def run(params, prompt, length):
        return prefill(params, cfg, prompt, p_bucket,
                       logit_positions=length[None] - 1)

    return jax.jit(run)


@functools.cache
def _compiled_prefix_admit(cfg: LlamaConfig, p_bucket: int, s_bucket: int,
                           max_len: int, temperature: float,
                           top_k: Optional[int], top_p: Optional[float]):
    """Admit one request as (cached prefix, fresh suffix) into one slot:

    1. file the prefix's cache rows into positions ``< plen`` of the
       slot (masked by position — bucket junk above ``plen`` must NOT
       land, suffix positions would attend it);
    2. ingest the suffix through :func:`chunk_decode_step` at positions
       ``plen ..`` — write-then-attend against the slot's own rows, the
       decode-path semantics, so the result is exactly what a full
       prefill of prefix+suffix would have produced;
    3. sample the first token from the suffix's last real position.

    One compile per (prefix bucket, suffix bucket).
    """
    from .speculative import chunk_decode_step

    rope = cfg_rope_tables(cfg, max_len)

    def run(params, cache, prefix_small, plen, suffix, s_len, slot, key):
        # Slot rows out: [L, 1, Hkv, max_len, ...] per leaf.
        rows = {
            name: lax.dynamic_slice(
                cache[name], (0, slot) + (0,) * (cache[name].ndim - 2),
                (cache[name].shape[0], 1) + cache[name].shape[2:])
            for name in cache
        }

        def merge(row, pre):
            # Prefix rows land where position < plen; everything else
            # keeps the slot's existing contents.  The T axis sits at
            # index 3 in EVERY cache leaf (k/v and the int8 scales).
            padded = lax.dynamic_update_slice(
                jnp.zeros_like(row), pre, (0,) * row.ndim)
            keep = (jnp.arange(row.shape[3]) < plen).reshape(
                (1, 1, 1, -1) + (1,) * (row.ndim - 4))
            return jnp.where(keep, padded, row)

        rows = {name: merge(rows[name], prefix_small[name])
                for name in rows}
        # Suffix ingestion: columns >= s_len are junk at positions above
        # the cursor — masked out of every real token's attention and
        # overwritten by decode before the cursor reaches them (the
        # standard covering argument).
        logits, rows = chunk_decode_step(params, rows, suffix, plen[None],
                                         cfg, rope)
        last = jnp.take_along_axis(
            logits, (s_len - 1)[None, None, None], axis=1)[:, 0]
        # rows are full-T slot rows — _write_slot_and_sample's T' = T.
        return _write_slot_and_sample(cache, rows, last, slot, key,
                                      temperature, top_k, top_p)

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_rolling_admit(cfg: LlamaConfig, temperature: float,
                            top_k: Optional[int], top_p: Optional[float]):
    """Rolling-cache admission, final part: write the request's [L, 1,
    Hkv, W, D] rolling cache into the slot and sample the first token."""

    def run(cache, small, logits, slot, key):
        return _write_slot_and_sample(cache, small, logits, slot, key,
                                      temperature, top_k, top_p)

    return jax.jit(run, donate_argnums=(0,))


# Chunk-width denominations for rolling admission: covering the prompt
# greedily with these bounds admission to <= 3 compiled chunk programs per
# config and <= P/64 + 7 + 7 dispatches — arbitrary prompt lengths never
# trigger fresh XLA compiles mid-serve (the compile explosion prompt
# bucketing prevents on the dense path).
ROLLING_ADMIT_WIDTHS = (64, 8, 1)


def _rolling_prefill_state(params, cfg: LlamaConfig, prompt: np.ndarray):
    """(next_logits [1, V], rolling cache [L, 1, Hkv, W, D]) for one
    prompt via denomination-scheduled ``prefill_rolling``.  Shared by
    admission and the serving tests' single-request oracle."""
    from .generate import prefill_rolling

    return prefill_rolling(params, cfg, jnp.asarray(prompt[None], jnp.int32),
                           widths=ROLLING_ADMIT_WIDTHS)


@functools.cache
def _compiled_chunk(cfg: LlamaConfig, n_slots: int, max_len: int, chunk: int,
                    temperature: float, top_k: Optional[int],
                    top_p: Optional[float], eos_id: Optional[int],
                    rolling: bool = False):
    """Advance every live slot ``chunk`` tokens in ONE dispatch.

    Per step: the pending token (at its slot's cursor) runs
    ``decode_step`` with per-row positions, the next token is sampled,
    budgets/eos update liveness.  Emits ``(tokens [chunk, B], mask
    [chunk, B])`` — mask marks which emissions are real (slot was live
    when its PENDING token was consumed, i.e. the sampled token continues
    a real request).  ``rolling``: the cache is circular per slot
    (``max_len`` is the rope horizon, not the cache size).
    """
    rope = cfg_rope_tables(cfg, max_len)

    def run(params, cache, token, pos, live, remaining, key):
        step = make_chunk_scan_step(
            lambda cache, token, pos: decode_step(
                params, cache, token, pos, cfg, rope, rolling=rolling),
            max_len, temperature, top_k, top_p, eos_id)
        (cache, token, pos, live, remaining, key), (toks, mask) = lax.scan(
            step, (cache, token, pos, live, remaining, key), None,
            length=chunk)
        return cache, token, pos, live, remaining, key, toks, mask

    return jax.jit(run, donate_argnums=(1,))


def make_chunk_scan_step(decode_one, max_len: int, temperature: float,
                         top_k, top_p, eos_id):
    """THE per-step body of every chunked serving loop — dense and paged
    (models/paged.py) scan exactly this, so the liveness/eos/budget/
    emission semantics cannot drift between cache layouts.
    ``decode_one(cache, token, pos) -> (logits, cache)``."""

    def step(carry, _):
        cache, token, pos, live, remaining, key = carry
        logits, cache = decode_one(cache, token, pos)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, sub, temperature, top_k, top_p)
        emit_live = live & (remaining > 0)
        if eos_id is not None:
            newly_done = emit_live & (nxt == eos_id)
        else:
            newly_done = jnp.zeros_like(emit_live)
        remaining = remaining - emit_live.astype(jnp.int32)
        live = emit_live & ~newly_done & (remaining > 0) & (
            pos + 2 < max_len)
        # Dead slots freeze: cursor stays, pending token irrelevant
        # (their cache writes land on a position admission or the
        # cursor overwrites before any read — or, paged, in the trash
        # page).
        pos = pos + emit_live.astype(jnp.int32)
        token = jnp.where(emit_live, nxt, token)
        return (cache, token, pos, live, remaining, key), (nxt, emit_live)

    return step


class SlotServer:
    """Continuous-batching front end over the compiled admit/decode programs.

    >>> srv = SlotServer(params, cfg, n_slots=4, max_len=512)
    >>> rid = srv.submit([1, 2, 3], max_new_tokens=32)
    >>> done = srv.run()          # {rid: np.ndarray of generated tokens}

    ``submit`` queues; ``step()`` admits pending requests into free slots
    and advances one decode chunk, returning newly finished requests;
    ``run()`` loops until everything queued has finished.  Generated
    tokens INCLUDE the terminating eos (when ``eos_id`` fires).

    PREFIX CACHING: ``register_prefix(tokens)`` prefills a shared prefix
    (system prompt, few-shot preamble) once; ``submit(suffix,
    prefix=pid)`` requests then admit by copying the prefix's cache rows
    into the slot (masked by position) and ingesting only the suffix
    through one chunk forward — admission cost scales with the suffix,
    not the full prompt, and the generated text is exactly
    ``generate(prefix + suffix)``'s (tests/test_serving.py).  MoE models
    serve when their capacity is provably dropless
    (``moe_capacity_factor >= n_experts``, the Mixtral conversion
    default).
    """

    def __init__(self, params, cfg: LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, chunk: int = 8,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, eos_id: Optional[int] = None,
                 prompt_buckets=None, seed: int = 0, on_tokens=None):
        from .moe import require_dropless

        # Cohabiting slots share the batch-wide expert capacity; only
        # provable droplessness keeps them independent (moe.py, the
        # single source of the rule).
        require_dropless(cfg, "continuous batching")
        # LongRoPE: admit (bucket-length tables) and decode (max_len
        # tables) must share one factor regime — pin it to the serving
        # horizon (llama.resolve_longrope).
        from .llama import resolve_longrope

        cfg = resolve_longrope(cfg, max_len)
        self.rolling = cfg.sliding_window is not None
        if n_slots < 1 or chunk < 1:
            # Zero slots/chunk would make run() spin forever, not error.
            raise ValueError(f"need n_slots >= 1 and chunk >= 1, got "
                             f"{n_slots}/{chunk}")
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.chunk = chunk
        self.sampling = (float(temperature), top_k, top_p)
        self.eos_id = None if eos_id is None else int(eos_id)
        if self.rolling:
            self.buckets = ()  # rolling admission never buckets prompts
        else:
            if prompt_buckets is None:
                b, buckets = 32, []
                while b < max_len:
                    buckets.append(b)
                    b *= 2
                # Always cover the full cache: a prompt up to max_len - 1
                # must have a bucket, or submit-accepted requests would
                # die at admission time.
                buckets.append(max_len)
                prompt_buckets = tuple(buckets)
            self.buckets = tuple(sorted(set(prompt_buckets)))
            if self.buckets[-1] > max_len:
                raise ValueError(f"bucket {self.buckets[-1]} exceeds "
                                 f"max_len={max_len}")
        self.key = jax.random.PRNGKey(seed)

        # Rolling (sliding-window) models keep an O(window) circular cache
        # per slot; max_len then bounds the ROPE horizon (prompt + budget),
        # not cache memory.  (_make_cache is a subclass hook: the paged
        # server allocates a shared page pool instead — models/paged.py.)
        self.cache = self._make_cache()
        self.token = jnp.zeros((n_slots,), jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.live = jnp.zeros((n_slots,), bool)
        self.remaining = jnp.zeros((n_slots,), jnp.int32)

        self._next_rid = 0
        self._pending: deque = deque()
        self._slot_rid: dict[int, int] = {}
        self._collected: dict[int, list] = {}
        self._prefixes: dict[int, tuple] = {}  # pid -> (small, plen)
        # Streaming hook: ``on_tokens(rid, tokens, done)`` fires inside
        # step() — once per request per step with that step's new tokens
        # (done=False), and exactly once with ``([], True)`` when the
        # request finishes.  The transport bridge
        # (models/remote_serving.py) rides this to stream tokens over the
        # wire without waiting for full completion.
        self.on_tokens = on_tokens
        self._post_init()
        self._next_pid = 0

    # ------------------------------------------------------------ intake
    def _make_cache(self):
        return (init_rolling_cache(self.cfg, self.n_slots) if self.rolling
                else init_cache(self.cfg, self.n_slots, self.max_len))

    def _post_init(self) -> None:
        """Subclass hook, called at the end of __init__."""

    def _on_slot_freed(self, slot: int) -> None:
        """Subclass hook: a slot's request finished or was cancelled (the
        paged server returns its pages to the pool here)."""

    def register_prefix(self, tokens) -> int:
        """Prefill a shared PREFIX (system prompt, few-shot preamble) once
        and return its id; requests submitted with ``prefix=pid`` reuse
        its cache rows instead of re-prefilling them — admission then
        costs one suffix-bucket chunk ingest, not a full-prompt prefill.
        The prefix cache lives in host-visible HBM ([L, 1, Hkv, bucket,
        D] per prefix) until :meth:`drop_prefix`."""
        if self.rolling:
            raise ValueError("prefix caching needs the dense slot cache; "
                             "rolling (sliding-window) slots rebuild their "
                             "window per request anyway")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if len(tokens) < 1:
            raise ValueError("empty prefix")
        if len(tokens) + self.buckets[0] + 1 > self.max_len:
            # The suffix ingest writes bucket-wide, so a prefix must leave
            # at least the SMALLEST bucket plus one generated token —
            # checked here, before a full prefill is burned on a prefix no
            # submit() could ever use.
            raise ValueError(
                f"prefix ({len(tokens)}) + smallest suffix bucket "
                f"({self.buckets[0]}) + 1 exceeds max_len={self.max_len}")
        pb = _bucket(len(tokens), self.buckets)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :len(tokens)] = tokens
        reg = _compiled_prefix_register(self.cfg, pb)
        _logits, small = reg(self.params, jnp.asarray(padded),
                             jnp.asarray(len(tokens), jnp.int32))
        pid = self._next_pid
        self._next_pid += 1
        self._prefixes[pid] = (small, len(tokens))
        return pid

    def drop_prefix(self, pid: int) -> None:
        """Free a registered prefix's cache rows.  Refuses while a QUEUED
        request still references it — dropping under it would otherwise
        blow up mid-step after the request left the queue, destroying
        that step's already-harvested results (admitted requests no
        longer need the prefix; only the queue is checked)."""
        if any(p == pid for _rid, _pr, _mn, p in self._pending):
            raise ValueError(
                f"prefix {pid} is still referenced by queued requests; "
                f"run()/step() them first")
        del self._prefixes[pid]

    def submit(self, prompt, max_new_tokens: int,
               prefix: Optional[int] = None) -> int:
        """Queue one request; returns its id (resolved by step()/run()).

        ``prefix``: a :meth:`register_prefix` id — ``prompt`` is then the
        SUFFIX continuing it (the generated text continues
        ``prefix_tokens + prompt``)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        plen = 0
        if prefix is not None:
            if prefix not in self._prefixes:
                raise KeyError(f"unknown prefix id {prefix}")
            plen = self._prefixes[prefix][1]
        if plen + len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prefix ({plen}) + prompt ({len(prompt)}) + max_new "
                f"({max_new_tokens}) exceeds max_len={self.max_len}")
        # Reject un-bucketable/un-placeable requests NOW, not at admission
        # time after the request has left the queue.
        if prefix is not None:
            sb = _bucket(len(prompt), self.buckets)
            if plen + sb > self.max_len:
                raise ValueError(
                    f"prefix ({plen}) + suffix bucket ({sb}, rounded up "
                    f"from {len(prompt)}) exceeds max_len={self.max_len}: "
                    f"the suffix ingest writes bucket-wide")
        elif not self.rolling:
            _bucket(len(prompt), self.buckets)
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append((rid, prompt, int(max_new_tokens), prefix))
        return rid

    # ------------------------------------------------------------- engine
    def _admit(self, slot: int, rid: int, prompt: np.ndarray,
               max_new: int, prefix: Optional[int] = None) -> None:
        self.key, sub = jax.random.split(self.key)
        plen = 0
        if prefix is not None:
            if prefix not in self._prefixes:
                raise KeyError(
                    f"prefix {prefix} was dropped while request {rid} "
                    f"waited in the queue")
            small, plen = self._prefixes[prefix]
            sb = _bucket(len(prompt), self.buckets)
            padded = np.zeros((1, sb), np.int32)
            padded[0, :len(prompt)] = prompt
            admit = _compiled_prefix_admit(
                self.cfg, small["k"].shape[3], sb, self.max_len,
                *self.sampling)
            self.cache, tok = admit(
                self.params, self.cache, small,
                jnp.asarray(plen, jnp.int32), jnp.asarray(padded),
                jnp.asarray(len(prompt), jnp.int32),
                jnp.asarray(slot, jnp.int32), sub)
        elif self.rolling:
            # Chunked O(window) prefill with denomination widths: at most
            # len(ROLLING_ADMIT_WIDTHS) compiled programs, any prompt
            # length.
            logits, small = _rolling_prefill_state(
                self.params, self.cfg, prompt)
            admit = _compiled_rolling_admit(self.cfg, *self.sampling)
            self.cache, tok = admit(self.cache, small, logits,
                                    jnp.asarray(slot, jnp.int32), sub)
        else:
            pb = _bucket(len(prompt), self.buckets)
            padded = np.zeros((1, pb), np.int32)
            padded[0, :len(prompt)] = prompt
            admit = _compiled_admit(self.cfg, pb, *self.sampling)
            self.cache, tok = admit(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(len(prompt), jnp.int32),
                jnp.asarray(slot, jnp.int32), sub)
        self._finish_admit(slot, rid, tok, plen + len(prompt), max_new)

    def _finish_admit(self, slot: int, rid: int, tok, cursor: int,
                      max_new: int) -> None:
        """Shared tail of every admission path (dense, prefix, rolling,
        paged): record the first token, fire the streaming hook, and set
        the slot's cursor/liveness/budget."""
        tok_host = int(tok)
        self._slot_rid[slot] = rid
        self._collected[rid] = [tok_host]
        if self.on_tokens is not None:
            self.on_tokens(rid, [tok_host], False)
            if rid not in self._collected:
                # The callback cancel()ed this very request; writing the
                # slot state below would resurrect it as an unrouted
                # zombie that decodes garbage until slot reuse.
                return
        done = (max_new == 1 or
                (self.eos_id is not None and tok_host == self.eos_id))
        self.token = self.token.at[slot].set(tok_host)
        self.pos = self.pos.at[slot].set(cursor)
        self.live = self.live.at[slot].set(not done)
        self.remaining = self.remaining.at[slot].set(max_new - 1)

    def cancel(self, rid: int) -> bool:
        """Abort a request: de-queue it if pending, else kill its slot so
        the next step() frees it for waiting work (the transport bridge
        calls this when a client disconnects or sends CANCEL — decoding
        for a peer that will never read the tokens is wasted chip time).

        Returns True if the request was found (pending or in a slot);
        a finished/unknown rid returns False.  A cancelled request is
        NOT reported by step()/run() and emits no on_tokens done event —
        cancellation is the caller declaring the stream dead."""
        for i, (qrid, *_rest) in enumerate(self._pending):
            if qrid == rid:
                del self._pending[i]
                return True
        for slot, srid in self._slot_rid.items():
            if srid == rid:
                self.live = self.live.at[slot].set(False)
                self.remaining = self.remaining.at[slot].set(0)
                del self._slot_rid[slot]
                self._collected.pop(rid, None)
                self._on_slot_freed(slot)
                return True
        return False

    def _harvest_dead(self, finished: dict) -> None:
        live = np.asarray(self.live)
        # Snapshot + tolerant pops: a done-event on_tokens callback may
        # cancel() another request that finished in this same step,
        # removing its entries before the loop reaches them.
        for slot, rid in list(self._slot_rid.items()):
            if not live[slot]:
                if rid not in self._collected:
                    self._slot_rid.pop(slot, None)  # cancelled mid-loop
                    continue
                finished[rid] = np.asarray(self._collected.pop(rid),
                                           np.int32)
                self._slot_rid.pop(slot, None)
                self._on_slot_freed(slot)
                if self.on_tokens is not None:
                    self.on_tokens(rid, [], True)

    def step(self) -> dict:
        """Admit what fits, decode one chunk; returns {rid: tokens} for
        requests that finished during this step."""
        finished: dict = {}
        self._harvest_dead(finished)  # 1-token/instant-eos admissions
        free = [s for s in range(self.n_slots) if s not in self._slot_rid]
        while free and self._pending:
            rid, prompt, max_new, prefix = self._pending.popleft()
            try:
                self._admit(free.pop(0), rid, prompt, max_new, prefix)
            except RuntimeError:
                # Transient resource exhaustion (the paged server's pool):
                # the request STAYS QUEUED — in-flight work frees capacity
                # and a later step admits it (the class docstring's
                # "callers keep it queued / retry" contract).
                self._pending.appendleft((rid, prompt, max_new, prefix))
                break
        self._harvest_dead(finished)
        if not self._slot_rid:
            return finished

        self.key, sub = jax.random.split(self.key)
        toks, mask = self._run_chunk(sub)
        toks = np.asarray(toks)
        mask = np.asarray(mask)
        # Snapshot: an on_tokens callback may legally cancel() a request
        # (its own or another), which mutates _slot_rid/_collected.
        for slot, rid in list(self._slot_rid.items()):
            if rid not in self._collected:
                continue  # cancelled by an earlier callback this step
            new = [int(t) for t, m in zip(toks[:, slot], mask[:, slot]) if m]
            self._collected[rid].extend(new)
            if self.on_tokens is not None and new:
                self.on_tokens(rid, new, False)
        self._harvest_dead(finished)
        return finished

    @property
    def busy(self) -> bool:
        """True while any request is queued or occupying a slot."""
        return bool(self._pending or self._slot_rid)

    def _run_chunk(self, sub):
        """Advance one decode chunk (subclass hook: the paged server runs
        its page-table program here); returns (tokens, mask)."""
        run = _compiled_chunk(self.cfg, self.n_slots, self.max_len,
                              self.chunk, *self.sampling, self.eos_id,
                              rolling=self.rolling)
        (self.cache, self.token, self.pos, self.live, self.remaining,
         _key, toks, mask) = run(self.params, self.cache, self.token,
                                 self.pos, self.live, self.remaining, sub)
        return toks, mask

    def run(self) -> dict:
        """Drive step() until every submitted request has finished."""
        finished: dict = {}
        while self.busy:
            finished.update(self.step())
        return finished
