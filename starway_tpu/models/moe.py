"""Switch-style mixture-of-experts FFN with expert parallelism.

Top-1 routing with static capacity (Switch Transformer recipe): one-hot
dispatch/combine tensors keep every shape static so XLA can plan the
expert all-to-all, and the expert weight tables shard over the mesh "ep"
axis (``moe_specs``) -- GSPMD inserts the dispatch collectives over ICI.
Gives the framework a real expert-parallel (EP) axis next to dp/tp/sp/pp.

All einsum contractions run in the model compute dtype with f32 router
statistics; the load-balancing auxiliary loss is the standard
``E * mean(frac_tokens_e * mean_router_prob_e)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_moe_params(key, n_layers: int, n_experts: int, d_model: int,
                    d_ff: int, dtype) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    return {
        "router": norm(k1, (n_layers, d_model, n_experts), d_model**-0.5),
        "w_in": norm(k2, (n_layers, n_experts, d_model, d_ff), d_model**-0.5),
        "w_out": norm(k3, (n_layers, n_experts, d_ff, d_model), d_ff**-0.5),
    }


def moe_specs() -> dict:
    """PartitionSpecs: experts shard over the "ep" mesh axis."""
    return {
        "router": P(None, None, None),
        "w_in": P(None, "ep", None, None),
        "w_out": P(None, "ep", None, None),
    }


def switch_moe(x, router_w, w_in, w_out, *, capacity_factor: float = 1.25):
    """x: [B, S, D] -> (y: [B, S, D], aux_loss: scalar f32).

    Tokens over capacity are dropped (their residual path carries them),
    matching the Switch formulation.
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    t = b * s
    xt = x.reshape(t, d)

    logits = (xt @ router_w).astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, E]
    gate = jnp.sum(probs * onehot, axis=-1)  # [T]

    # Load-balancing aux loss (Switch eq. 4).
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)

    capacity = max(1, int(t / e * capacity_factor))
    pos_in_expert = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1.0  # [T]
    keep = pos_in_expert < capacity
    # [T, E, C] dispatch tensor: token -> (expert, slot).
    disp = (onehot * keep[:, None])[:, :, None] * jax.nn.one_hot(
        jnp.clip(pos_in_expert, 0, capacity - 1).astype(jnp.int32),
        capacity, dtype=jnp.float32,
    )[:, None, :]

    cd = x.dtype
    expert_in = jnp.einsum("tec,td->ecd", disp.astype(cd), xt)  # [E, C, D]
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, w_in).astype(jnp.float32)
    ).astype(cd)
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_out)  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", disp.astype(cd), expert_out)
    y = y * gate.astype(cd)[:, None]
    return y.reshape(b, s, d), aux
