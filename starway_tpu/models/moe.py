"""Mixture-of-experts FFN with expert parallelism and top-k routing.

Switch/GShard-style static-capacity routing built TPU-first:

* **Dispatch is scatter/gather, not a dense one-hot einsum.**  Each routed
  (token, choice) computes an integer slot ``expert * C + position`` and the
  token rows are scattered into an ``[E*C, D]`` send buffer (overflow goes to
  a trash row) -- O(T*k) index work plus the O(E*C*D) = O(T*cf*D) buffer the
  all-to-all needs anyway, instead of the O(T*E*C) dispatch tensor of the
  textbook formulation.  Shapes stay static so XLA can plan the collectives.
* **Top-k routing** (k=1 Switch, k=2 GShard/Mixtral): first choices take
  capacity priority over second choices; top-2 gates are renormalised over
  the chosen pair.
* Two views of the same math:
  :func:`switch_moe` -- global view; expert tables shard over the mesh "ep"
  axis via :func:`moe_specs` and GSPMD inserts the dispatch collectives.
  :func:`sharded_switch_moe` -- local (shard_map) view with an explicit
  ``lax.all_to_all`` over the "ep" axis, for when the collective schedule
  should be pinned rather than inferred; :func:`make_sharded_moe` wraps it
  for use as ``forward(..., moe_fn=...)``.

The load-balancing auxiliary loss is the standard
``E * sum_e(frac_first_choice_e * mean_router_prob_e)`` (Switch eq. 4;
reduces to GShard's aux for k>=2 with first-choice fractions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def init_moe_params(key, n_layers: int, n_experts: int, d_model: int,
                    d_ff: int, dtype, swiglu: bool = False) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    out = {
        "router": norm(k1, (n_layers, d_model, n_experts), d_model**-0.5),
        "w_in": norm(k2, (n_layers, n_experts, d_model, d_ff), d_model**-0.5),
        "w_out": norm(k3, (n_layers, n_experts, d_ff, d_model), d_ff**-0.5),
    }
    if swiglu:
        # Mixtral-style SwiGLU experts; _expert_ffn keys off the leaf.
        out["w_gate"] = norm(k4, (n_layers, n_experts, d_model, d_ff),
                             d_model**-0.5)
    return out


def moe_specs(swiglu: bool = False) -> dict:
    """PartitionSpecs: experts shard over the "ep" mesh axis."""
    out = {
        "router": P(None, None, None),
        "w_in": P(None, "ep", None, None),
        "w_out": P(None, "ep", None, None),
    }
    if swiglu:
        out["w_gate"] = P(None, "ep", None, None)
    return out


def require_dropless(cfg, context: str) -> None:
    """Raise unless ``cfg`` is dense or PROVABLY dropless MoE
    (``moe_capacity_factor >= n_experts`` -> capacity >= T * k for any
    token count, :func:`moe_capacity`'s ceiling).  The single source of
    the rule every shape-sensitive entry point shares: ragged
    generation, continuous batching, and the speculative chunk verify
    all rely on routing being shape-invariant, which only droplessness
    guarantees."""
    if cfg.n_experts > 0 and cfg.moe_capacity_factor < cfg.n_experts:
        raise ValueError(
            f"{context} needs dense FFNs or provably-dropless MoE: expert "
            f"capacity is computed per forward, so routing could differ "
            f"across forward shapes; set moe_capacity_factor >= n_experts "
            f"(= {cfg.n_experts}) to make drops impossible (the Mixtral "
            f"conversion default)")


def moe_capacity(n_assignments: int, n_experts: int,
                 capacity_factor: float) -> int:
    """Static per-expert capacity for ``n_assignments`` routed (token,
    choice) pairs -- ``T * k``, not ``T`` (GShard scales capacity by k, or
    top-2 would drop second choices even under a balanced router).

    Ceiling, not truncation: ``capacity_factor >= n_experts`` must yield
    capacity ``>= n_assignments`` — PROVABLY dropless for any routing —
    because ragged MoE generation's pad-safety argument
    (models/generate.py) rests on exactly that guarantee; ``int()`` would
    lose it off float division for non-power-of-two expert counts."""
    import math

    return max(1, math.ceil(n_assignments * capacity_factor / n_experts
                            - 1e-9))


def _route(xt, router_w, k: int):
    """Router statistics for ``xt [T, D]``.

    Returns ``(expert_flat [T*k], gate_flat [T*k] f32, aux scalar f32)``
    in choice-major order (all first choices in token order, then all
    second choices, ...), so a cumsum over the flat order gives first
    choices capacity priority.
    """
    e = router_w.shape[-1]
    logits = (xt @ router_w).astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, k)  # [T, k]
    if k > 1:
        # Mixtral/GShard: renormalise the chosen gates over the pair.
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    # Load-balancing aux loss from FIRST choices (Switch eq. 4).
    frac_tokens = jnp.mean(
        jax.nn.one_hot(top_i[:, 0], e, dtype=jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)

    expert_flat = top_i.T.reshape(-1)  # choice-major
    gate_flat = top_p.T.reshape(-1)
    return expert_flat, gate_flat, aux


def _dispatch_slots(expert_flat, n_experts: int, capacity: int):
    """Slot index per routed (token, choice): ``expert * C + position``.

    ``position`` counts prior assignments to the same expert in flat order
    (choice-major -> first choices win capacity).  Overflow maps to the
    trash slot ``E*C``.  Returns ``(slot [T*k] int32, keep [T*k] bool,
    counts [E] int32)`` — counts is each expert's routed-assignment total,
    a byproduct of the capacity numbering that :func:`_routing_stats`
    reuses for free.
    """
    # int32 counting stays exact however many tokens are routed (an f32
    # cumsum would misnumber positions past 2^24 assignments).
    onehot = jax.nn.one_hot(expert_flat, n_experts, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1
    keep = pos < capacity
    pos = jnp.clip(pos, 0, capacity - 1)
    slot = jnp.where(keep, expert_flat * capacity + pos,
                     n_experts * capacity)
    return slot.astype(jnp.int32), keep, jnp.sum(onehot, axis=0)


def _scatter_tokens(xt, slot, k: int, n_experts: int, capacity: int):
    """Gather routed token rows into the ``[E*C, D]`` send buffer."""
    t, d = xt.shape
    token_flat = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    buf = jnp.zeros((n_experts * capacity + 1, d), xt.dtype)
    return buf.at[slot].set(xt[token_flat], mode="drop")[:-1]


def _combine_tokens(y_buf, slot, keep, gate_flat, k: int, t: int):
    """Inverse of :func:`_scatter_tokens`: gather each routed choice's
    expert output, weight by its gate, sum the k choices per token."""
    ec = y_buf.shape[0]
    y = y_buf[jnp.clip(slot, 0, ec - 1)]  # [T*k, D]
    w = (gate_flat * keep.astype(jnp.float32)).astype(y.dtype)
    return jnp.sum((y * w[:, None]).reshape(k, t, -1), axis=0)


def _routing_stats(expert_counts, keep):
    """Router-health metrics from quantities the dispatch already computed
    (``_dispatch_slots``' per-expert counts; no extra collective, no second
    one-hot): ``drop_fraction`` -- share of routed (token, choice) pairs
    that fell over capacity and were dropped to the residual path -- and
    ``expert_load [E]`` -- each expert's share of routed assignments (1/E
    everywhere = perfectly balanced; a collapsing router concentrates mass
    on few experts and shows a rising drop_fraction)."""
    load = expert_counts.astype(jnp.float32) / keep.shape[0]
    drop = 1.0 - jnp.mean(keep.astype(jnp.float32))
    return {"drop_fraction": drop, "expert_load": load}


def _expert_ffn(expert_in, w_in, w_out, w_gate=None):
    """``[E, C', D] -> [E, C', D]`` through each expert's MLP: gelu
    two-matrix (Switch-style) by default, or SwiGLU when ``w_gate``
    [E, D, F] is given (Mixtral-style:
    ``(silu(x @ w_gate) * (x @ w_in)) @ w_out``)."""
    cd = expert_in.dtype
    if w_gate is not None:
        g = jax.nn.silu(
            jnp.einsum("ecd,edf->ecf", expert_in, w_gate).astype(jnp.float32)
        ).astype(cd)
        h = g * jnp.einsum("ecd,edf->ecf", expert_in, w_in)
    else:
        h = jax.nn.gelu(
            jnp.einsum("ecd,edf->ecf", expert_in, w_in).astype(jnp.float32)
        ).astype(cd)
    return jnp.einsum("ecf,efd->ecd", h, w_out)


def switch_moe(x, router_w, w_in, w_out, *, capacity_factor: float = 1.25,
               k: int = 1, with_stats: bool = False, w_gate=None):
    """x: [B, S, D] -> (y: [B, S, D], aux_loss: scalar f32).  Global view.

    Tokens over capacity are dropped (their residual path carries them).
    Under a GSPMD mesh with ``moe_specs`` the expert dimension of the
    ``[E, C, D]`` buffers shards over "ep" and XLA inserts the all-to-alls.

    ``with_stats``: also return :func:`_routing_stats` (drop fraction +
    per-expert load) as a third element, so a collapsing router is visible
    from the training loop instead of silently dropping tokens.
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    t = b * s
    xt = x.reshape(t, d)
    capacity = moe_capacity(t * k, e, capacity_factor)

    expert_flat, gate_flat, aux = _route(xt, router_w, k)
    slot, keep, counts = _dispatch_slots(expert_flat, e, capacity)
    expert_in = _scatter_tokens(xt, slot, k, e, capacity).reshape(e, capacity, d)
    expert_out = _expert_ffn(expert_in, w_in, w_out, w_gate)
    y = _combine_tokens(expert_out.reshape(e * capacity, d), slot, keep,
                        gate_flat, k, t)
    y = y.reshape(b, s, d)
    if with_stats:
        return y, aux, _routing_stats(counts, keep)
    return y, aux


def sharded_switch_moe(x, router_w, w_in, w_out, axis_name: str, *,
                       capacity_factor: float = 1.25, k: int = 1,
                       with_stats: bool = False, w_gate=None):
    """Local (shard_map) view with an explicit expert all-to-all.

    ``x [B_loc, S_loc, D]``: this shard's tokens.  ``w_in/w_out
    [E_loc, D, F] / [E_loc, F, D]``: this shard's experts (E = E_loc * ep).
    Capacity is per (source shard, expert) from the LOCAL token count, so
    the all-to-all payload is O(T_loc * cf * D) per device.

    The aux loss is the pmean over the axis of per-shard aux statistics --
    statistically the global Switch aux (equal shard sizes) though not
    bit-identical to the global-view formula (mean of products vs product
    of means across shards).

    ``with_stats``: also return drop fraction + per-expert load (see
    :func:`_routing_stats`).  The stats ride the SAME pmean the aux loss
    already pays (stacked into one small vector) -- no new collective in
    the hot path.
    """
    ep = lax.axis_size(axis_name)
    b, s, d = x.shape
    e_loc = w_in.shape[0]
    e = e_loc * ep
    t = b * s
    xt = x.reshape(t, d)
    capacity = moe_capacity(t * k, e, capacity_factor)

    expert_flat, gate_flat, aux = _route(xt, router_w, k)
    slot, keep, counts = _dispatch_slots(expert_flat, e, capacity)
    send = _scatter_tokens(xt, slot, k, e, capacity)  # [E*C, D]

    # [ep, E_loc, C, D] -> all-to-all -> leading axis becomes source shard.
    send = send.reshape(ep, e_loc, capacity, d)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    # Each local expert sees the rows every shard bucketed for it.
    expert_in = recv.transpose(1, 0, 2, 3).reshape(e_loc, ep * capacity, d)
    expert_out = _expert_ffn(expert_in, w_in, w_out, w_gate)
    back = expert_out.reshape(e_loc, ep, capacity, d).transpose(1, 0, 2, 3)
    got = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)

    y = _combine_tokens(got.reshape(e * capacity, d), slot, keep, gate_flat,
                        k, t)
    y = y.reshape(b, s, d)
    if with_stats:
        stats = _routing_stats(counts, keep)
        packed = jnp.concatenate(
            [jnp.stack([aux, stats["drop_fraction"]]), stats["expert_load"]])
        packed = lax.pmean(packed, axis_name)
        return y, packed[0], {"drop_fraction": packed[1],
                              "expert_load": packed[2:]}
    return y, lax.pmean(aux, axis_name)


def make_sharded_moe(mesh, *, ep_axis: str = "ep", dp_axis: str = "dp",
                     capacity_factor: float = 1.25, k: int = 1,
                     with_stats: bool = False, swiglu: bool = False):
    """Build a ``moe_fn(x, router_w, w_in, w_out[, w_gate]) -> (y, aux)``
    running :func:`sharded_switch_moe` under shard_map: tokens shard over
    (dp, ep) -- batch over dp, sequence over ep -- experts over ep, and the
    dispatch rides one explicit ``all_to_all`` pair over the ep axis.

    Plug into ``forward(..., moe_fn=...)`` /
    ``make_train_step(..., moe_fn=...)``.  ``with_stats``: the built fn
    returns ``(y, aux, stats)`` with router-health metrics (drop fraction,
    per-expert load) pmean'd over the mesh.  ``swiglu``: the tree carries
    Mixtral-style ``w_gate`` experts (decoder_layer passes it through).
    """
    from ..parallel.sharding import shard_map_fn

    other_axes = tuple(a for a in mesh.axis_names if a != ep_axis)

    def local(x, router_w, w_in, w_out, w_gate=None):
        out = sharded_switch_moe(
            x, router_w, w_in, w_out, ep_axis, w_gate=w_gate,
            capacity_factor=capacity_factor, k=k, with_stats=with_stats)
        y, aux = out[0], out[1]
        # aux/stats are ep-uniform already; replicate across the remaining
        # axes so the scalars can leave the shard_map with spec P().
        if other_axes:
            aux = lax.pmean(aux, other_axes)
        if with_stats:
            stats = out[2]
            if other_axes:
                stats = jax.tree_util.tree_map(
                    lambda v: lax.pmean(v, other_axes), stats)
            return y, aux, stats
        return y, aux

    x_spec = P(dp_axis if dp_axis in mesh.shape else None, ep_axis, None)
    out_specs = (x_spec, P())
    if with_stats:
        out_specs = (x_spec, P(),
                     {"drop_fraction": P(), "expert_load": P(None)})
    e_spec = P(ep_axis, None, None)
    in_specs = (x_spec, P(None, None), e_spec, e_spec) + (
        (e_spec,) if swiglu else ())
    mapped = shard_map_fn(mesh, local, in_specs=in_specs,
                          out_specs=out_specs)
    if not swiglu:
        return mapped

    def fn(x, router_w, w_in, w_out, w_gate=None):
        # decoder_layer passes w_gate by KEYWORD; shard_map takes
        # positional args only — adapt.
        return mapped(x, router_w, w_in, w_out, w_gate)

    return fn
