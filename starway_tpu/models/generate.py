"""KV-cache inference for the Llama family: prefill + single-token decode.

Static-shape, jit-compiled decode: the cache holds ``max_len`` slots per
layer and attention masks by position, so one compiled step serves the whole
generation (``lax.scan`` over steps; no retracing, no dynamic shapes -- the
XLA-friendly decode loop).

The cache layout is scan-stacked like the parameters: ``k/v
[n_layers, B, Hkv, max_len, head_dim]``, updated in place with
``dynamic_update_slice`` (donate the cache under jit for in-place HBM
updates).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .llama import LlamaConfig, apply_rope, rmsnorm, rope_tables
from ..ops.attention import NEG_BIG, repeat_kv


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    hd = cfg.head_dim
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, hd)
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
    }


def _attend_cached(q, k_cache, v_cache, pos, n_rep, use_pallas=None):
    """q: [B, Hq, 1, D]; caches: [B, Hkv, T, D]; mask positions > pos.

    On TPU the pallas decode kernel (ops/pallas_decode.py) streams the
    grouped cache once instead of materialising ``repeat_kv`` — an
    ``n_rep``× HBM-bandwidth saving on the bandwidth-bound decode step.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from ..ops.pallas_decode import decode_attention

        return decode_attention(q, k_cache, v_cache, pos)
    k = repeat_kv(k_cache, n_rep)
    v = repeat_kv(v_cache, n_rep)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s / (q.shape[-1] ** 0.5)
    kv_pos = jnp.arange(k.shape[2])
    s = jnp.where((kv_pos <= pos)[None, None, None, :], s, NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def decode_step(params: dict, cache: dict, token, pos, cfg: LlamaConfig,
                rope=None):
    """One token in, next-token logits out.  token: [B] int32; pos: scalar
    position of ``token``.  Returns (logits [B, V], updated cache)."""
    B = token.shape[0]
    hd = cfg.head_dim
    n_rep = cfg.n_heads // cfg.n_kv_heads
    if rope is None:
        rope = rope_tables(cache["k"].shape[3], hd, cfg.rope_theta)
    cos, sin = rope
    cos_p = lax.dynamic_slice_in_dim(cos, pos, 1, axis=0)
    sin_p = lax.dynamic_slice_in_dim(sin, pos, 1, axis=0)

    h = params["embed"][token][:, None, :]  # [B, 1, D]

    def layer(carry, lp_and_cache):
        h, = carry
        lp, kc, vc = lp_and_cache
        x = rmsnorm(h, lp["attn_norm"], cfg.norm_eps)
        q = (x @ lp["wq"]).reshape(B, 1, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = (x @ lp["wk"]).reshape(B, 1, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        v = (x @ lp["wv"]).reshape(B, 1, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos_p, sin_p)
        k = apply_rope(k, cos_p, sin_p)
        kc = lax.dynamic_update_slice_in_dim(kc, k, pos, axis=2)
        vc = lax.dynamic_update_slice_in_dim(vc, v, pos, axis=2)
        o = _attend_cached(q, kc, vc, pos, n_rep)
        o = o.transpose(0, 2, 1, 3).reshape(B, 1, cfg.n_heads * hd)
        h = h + o @ lp["wo"]

        x = rmsnorm(h, lp["mlp_norm"], cfg.norm_eps)
        if cfg.n_experts > 0:
            from .moe import switch_moe

            y, _ = switch_moe(
                x, lp["moe"]["router"], lp["moe"]["w_in"], lp["moe"]["w_out"],
                capacity_factor=cfg.moe_capacity_factor,
            )
            h = h + y
        else:
            gate = jax.nn.silu((x @ lp["w_gate"]).astype(jnp.float32)).astype(x.dtype)
            h = h + (gate * (x @ lp["w_up"])) @ lp["w_down"]
        return (h,), (kc, vc)

    (h,), (k_new, v_new) = lax.scan(
        layer, (h,), (params["layers"], cache["k"], cache["v"])
    )
    h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = (h[:, 0, :] @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def generate(params: dict, cfg: LlamaConfig, prompt, max_new_tokens: int,
             *, temperature: float = 0.0, key: Optional[jax.Array] = None,
             max_len: Optional[int] = None):
    """Autoregressive generation.  prompt: [B, P] int32.  Returns
    [B, P + max_new_tokens].  temperature=0 -> greedy; otherwise softmax
    sampling with ``key``."""
    B, P = prompt.shape
    total = P + max_new_tokens
    if max_len is None:
        max_len = total
    elif max_len < total:
        # Without this, dynamic_update_slice clamps every position >= max_len
        # onto the last cache slot and generation silently corrupts.
        raise ValueError(
            f"max_len={max_len} is smaller than prompt + max_new_tokens={total}"
        )
    if temperature > 0 and key is None:
        key = jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, max_len)
    rope = rope_tables(max_len, cfg.head_dim, cfg.rope_theta)

    step = jax.jit(
        lambda p, c, t, pos: decode_step(p, c, t, pos, cfg, rope),
        donate_argnums=(1,),
    )

    # Prefill: run the prompt through the cached decode path one position at
    # a time (single compiled step; prompt lengths are short in the demos).
    logits = None
    for i in range(P):
        logits, cache = step(params, cache, prompt[:, i], i)

    tokens = [prompt]
    cur = None
    for i in range(max_new_tokens):
        if temperature > 0:
            key, sub = jax.random.split(key)
            cur = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            cur = jnp.argmax(logits, axis=-1)
        cur = cur.astype(jnp.int32)
        tokens.append(cur[:, None])
        if i + 1 < max_new_tokens:  # the final token needs no further logits
            logits, cache = step(params, cache, cur, P + i)
    return jnp.concatenate(tokens, axis=1)
