"""KV-cache inference for the Llama family: prefill + single-token decode.

Static-shape, jit-compiled decode: the cache holds ``max_len`` slots per
layer and attention masks by position, so one compiled step serves the whole
generation (``lax.scan`` over steps; no retracing, no dynamic shapes -- the
XLA-friendly decode loop).

The cache layout is scan-stacked like the parameters: ``k/v
[n_layers, B, Hkv, max_len, head_dim]``, updated in place with
``dynamic_update_slice`` (donate the cache under jit for in-place HBM
updates).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .llama import (LlamaConfig, apply_rope, cfg_rope_tables, embed_tokens,
                    forward, matmul_w, mlp_gate_act, qkv_proj, rmsnorm)
from ..ops.attention import NEG_BIG, repeat_kv


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    """Decode cache: ``k/v [n_layers, B, Hkv, max_len, head_dim]``.

    ``cfg.kv_quant == "int8"`` stores k/v as int8 plus per-token f32 scales
    ``k_scale/v_scale [n_layers, B, Hkv, max_len]`` (ops/quantize.py) —
    half the HBM bytes on the bandwidth-bound decode stream.  The scale
    keys' presence IS the format marker every consumer dispatches on.
    """
    hd = cfg.head_dim
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, hd)
    if cfg.kv_quant == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], jnp.float32),
            "v_scale": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
    }


def init_rolling_cache(cfg: LlamaConfig, batch: int) -> dict:
    """O(window) cache for sliding-window models: ``sliding_window`` slots
    per layer, written modulo the window (see ``decode_step(rolling=True)``).
    Generation length no longer bounds cache memory."""
    if cfg.sliding_window is None:
        raise ValueError("rolling caches require cfg.sliding_window")
    return init_cache(cfg, batch, cfg.sliding_window)


def _attend_cached(q, k_cache, v_cache, pos, n_rep, use_pallas=None,
                   window=None, k_scale=None, v_scale=None):
    """q: [B, Hq, C, D] — C consecutive query positions per row (C=1 is
    single-token decode; C>1 the speculative chunk verify, whose entries
    are already written: write-then-attend).  caches: [B, Hkv, T, D];
    row b's queries sit at ``pos[b] .. pos[b] + C - 1`` (``pos`` scalar
    or per-row [B]) and mask key positions above themselves; ``window``
    restricts to the last ``window`` positions (sliding-window models).
    ``k_scale``/``v_scale`` ([B, Hkv, T] f32): the caches are
    int8-quantized (ops/quantize.py) — the kernel streams them at half
    width; the lax path dequantizes up front.

    On TPU the pallas decode kernel (ops/pallas_decode.py) streams the
    grouped cache once instead of materialising ``repeat_kv`` — an
    ``n_rep``× HBM-bandwidth saving on the bandwidth-bound decode step
    (and only ~window bytes of it under a sliding window); C>1 just adds
    matmul rows to the same stream.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from ..ops.pallas_decode import decode_attention

        return decode_attention(q, k_cache, v_cache, pos, window=window,
                                k_scale=k_scale, v_scale=v_scale)
    if k_scale is not None:
        from ..ops.quantize import dequantize_kv

        k_cache = dequantize_kv(k_cache, k_scale, q.dtype)
        v_cache = dequantize_kv(v_cache, v_scale, q.dtype)
    k = repeat_kv(k_cache, n_rep)
    v = repeat_kv(v_cache, n_rep)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s / (q.shape[-1] ** 0.5)
    kv_pos = jnp.arange(k.shape[2])[None, None, None, :]
    qp = (jnp.asarray(pos).reshape(-1)[:, None, None, None]
          + jnp.arange(q.shape[2])[None, None, :, None])
    keep = kv_pos <= qp
    if window is not None:
        keep = keep & (kv_pos > qp - window)
    s = jnp.where(keep, s, NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def decode_step(params: dict, cache: dict, token, pos, cfg: LlamaConfig,
                rope=None, rolling: bool = False):
    """One token in, next-token logits out.  token: [B] int32; pos: the
    ABSOLUTE position of ``token`` — a scalar (aligned batch) or a per-row
    [B] vector (ragged batch: every row sits at its own cursor).  Returns
    (logits [B, V], updated cache).

    ``rolling``: the cache is a circular window of exactly
    ``cfg.sliding_window`` slots (``init_rolling_cache``) — writes go to
    ``pos % window``, and attention covers every warm slot with no window
    re-mask (the residents ARE the window; keys carry their absolute RoPE,
    and attention is permutation-invariant over keys, so slot order never
    matters).  Cache memory is O(window) for any generation length."""
    B = token.shape[0]
    hd = cfg.head_dim
    n_rep = cfg.n_heads // cfg.n_kv_heads
    T = cache["k"].shape[3]
    if rolling:
        if cfg.sliding_window is None or T != cfg.sliding_window:
            raise ValueError(
                f"rolling decode needs a cache of exactly sliding_window="
                f"{cfg.sliding_window} slots, got {T}")
    if rope is None:
        if rolling:
            # Absolute positions exceed the cache size; the caller knows the
            # true horizon, we don't.
            raise ValueError("rolling decode requires explicit rope tables")
        rope = cfg_rope_tables(cfg, T)
    cos, sin = rope
    pos = jnp.asarray(pos, jnp.int32)
    per_row = pos.ndim == 1
    slot = jax.lax.rem(pos, T) if rolling else pos
    if per_row:
        # [B, 1, 1, hd/2]: one rotation angle per row, broadcast over heads.
        cos_p = cos[pos][:, None, None, :]
        sin_p = sin[pos][:, None, None, :]

        def write(c, u):
            return jax.vmap(
                lambda cr, ur, p: lax.dynamic_update_slice_in_dim(
                    cr, ur, p, axis=1))(c, u, slot)
    else:
        cos_p = lax.dynamic_slice_in_dim(cos, pos, 1, axis=0)
        sin_p = lax.dynamic_slice_in_dim(sin, pos, 1, axis=0)

        def write(c, u):
            return lax.dynamic_update_slice_in_dim(c, u, slot, axis=2)

    h = embed_tokens(params, token, cfg)[:, None, :]  # [B, 1, D]

    def attend(q, lc):
        ksc, vsc = lc.get("k_scale"), lc.get("v_scale")
        if rolling:
            # Warm slots are exactly the window (we just overwrote the
            # oldest); cold-start slots (> pos) are masked by the clamped
            # position.  No window re-mask: absolute order is irrelevant.
            return _attend_cached(q, lc["k"], lc["v"],
                                  jnp.minimum(pos, T - 1), n_rep,
                                  k_scale=ksc, v_scale=vsc)
        return _attend_cached(q, lc["k"], lc["v"], pos, n_rep,
                              window=cfg.sliding_window,
                              k_scale=ksc, v_scale=vsc)

    h, out = cached_layer_scan(params, cache, h, cos_p, sin_p, cfg, write,
                               attend)
    h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = matmul_w(h[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, out


def cached_layer_scan(params, cache, h, cos_p, sin_p, cfg: LlamaConfig,
                      write, attend):
    """The ONE per-layer body of every cached decode path — decode_step's
    C=1 and the speculative chunk verify's C>1
    (models/speculative.py:chunk_decode_step) run exactly this: qkv
    projection, RoPE, quantize-on-write when the cache is int8, ``write``
    at the caller's cursor(s), ``attend(q, layer_cache)``, FFN (dense or
    MoE).  Sharing it is what keeps the pinned chunk==stepwise parity a
    tautology instead of a maintenance contract.

    h: [B, C, D] embedded inputs; ``write(c, u)`` places a [B, Hkv, C(,D)]
    update (values and, int8, scales — the T axis sits at the same index
    once the trailing D dim is dropped); ``attend`` returns [B, Hq, C, hd].
    Returns ``(h [B, C, D], new cache dict)``.
    """
    B, C = h.shape[0], h.shape[1]
    hd = cfg.head_dim
    quant = "k_scale" in cache  # int8 cache (init_cache's format marker)

    def layer(carry, xs):
        h, = carry
        if quant:
            lp, kc, vc, ksc, vsc = xs
        else:
            lp, kc, vc = xs
            ksc = vsc = None
        x = rmsnorm(h, lp["attn_norm"], cfg.norm_eps)
        q, k, v = qkv_proj(x, lp, cfg)
        q = apply_rope(q, cos_p, sin_p)
        k = apply_rope(k, cos_p, sin_p)
        if quant:
            from ..ops.quantize import quantize_kv

            # Quantize-on-write: the cache never holds a wide entry.
            k, k_s = quantize_kv(k)
            v, v_s = quantize_kv(v)
            ksc = write(ksc, k_s)
            vsc = write(vsc, v_s)
        kc = write(kc, k)
        vc = write(vc, v)
        layer_cache = {"k": kc, "v": vc}
        if quant:
            layer_cache["k_scale"], layer_cache["v_scale"] = ksc, vsc
        o = attend(q, layer_cache)
        o = o.transpose(0, 2, 1, 3).reshape(B, C, cfg.n_heads * hd)
        h = h + matmul_w(o, lp["wo"])

        x = rmsnorm(h, lp["mlp_norm"], cfg.norm_eps)
        if cfg.n_experts > 0:
            from .moe import switch_moe

            y, _ = switch_moe(
                x, lp["moe"]["router"], lp["moe"]["w_in"], lp["moe"]["w_out"],
                capacity_factor=cfg.moe_capacity_factor, k=cfg.moe_top_k,
                w_gate=lp["moe"].get("w_gate"),
            )
            h = h + y
        else:
            gate = mlp_gate_act(matmul_w(x, lp["w_gate"]), cfg).astype(x.dtype)
            h = h + matmul_w(gate * matmul_w(x, lp["w_up"]), lp["w_down"])
        return (h,), (kc, vc) + ((ksc, vsc) if quant else ())

    xs = (params["layers"], cache["k"], cache["v"])
    if quant:
        xs += (cache["k_scale"], cache["v_scale"])
    (h,), new = lax.scan(layer, (h,), xs)
    out = {"k": new[0], "v": new[1]}
    if quant:
        out["k_scale"], out["v_scale"] = new[2], new[3]
    return h, out


def prefill(params: dict, cfg: LlamaConfig, prompt,
            max_len: Optional[int] = None, attn_fn=None,
            logit_positions=None):
    """One parallel forward pass over the whole prompt -> the decode state.

    Returns ``(next_logits [B, V], cache)`` where the cache holds the
    post-RoPE grouped k/v of positions ``0..P-1`` (zero-padded to
    ``max_len``).  This is the flash-attention path over the prompt — one
    MXU-shaped dispatch instead of P bandwidth-bound cached decode steps,
    and bit-identical to stepping the prompt through ``decode_step``
    (pinned by tests/test_generate.py::test_prefill_matches_stepwise).

    ``logit_positions`` ([B] ints, ragged right-padded batches): the
    returned logits come from each row's own position instead of the last
    column (no [B, P, V] tensor is built either way).
    """
    B, P = prompt.shape
    if max_len is None:
        max_len = P
    elif max_len < P:
        raise ValueError(f"max_len={max_len} is smaller than the prompt ({P})")
    logits, _aux, (ks, vs) = forward(
        params, prompt, cfg, attn_fn, return_aux=True, return_kv=True,
        last_only=logit_positions is None, logit_positions=logit_positions,
    )
    cache = {"k": ks, "v": vs}
    if cfg.kv_quant == "int8":
        from ..ops.quantize import quantize_kv

        cache["k"], cache["k_scale"] = quantize_kv(ks)
        cache["v"], cache["v_scale"] = quantize_kv(vs)
    pad = max_len - P
    if pad:
        # Every leaf's T axis sits at index 3 (the scale arrays only drop
        # the trailing D dim) — same invariant the rolling gather relies on.
        cache = jax.tree_util.tree_map(
            lambda a: jnp.pad(
                a, ((0, 0),) * 3 + ((0, pad),) + ((0, 0),) * (a.ndim - 4)),
            cache)
    return logits[:, 0], cache


def prefill_rolling(params: dict, cfg: LlamaConfig, prompt, *,
                    chunk: Optional[int] = None, attn_fn=None,
                    widths=None):
    """Long-prompt prefill in O(window) memory: chunks of at most
    ``sliding_window`` tokens stream through the transformer, each chunk
    attending to the rolling cache (its own window's past) plus itself,
    merged with the online-softmax partial algebra
    (ops/attention.py::merge_partials).  Peak activation memory scales
    with ``chunk + window``, never the prompt — the missing piece between
    the O(window) decode cache and an O(S) full-prompt prefill.

    Returns ``(last_logits [B, V], rolling_cache)``; continue with
    ``decode_step(..., pos=P, rolling=True)`` (or hand both to a serving
    loop).  Matches the one-pass windowed prefill bit-close (pinned by
    tests/test_generate.py).  The chunk body is the same
    :func:`~starway_tpu.models.llama.decoder_layer` every other path uses
    (``attn_fn`` must be None: the chunk step owns its attention).

    ``widths`` (else ``chunk``): a DENOMINATION schedule, e.g. (64, 8, 1)
    — the prompt is covered greedily by these chunk widths (each capped at
    the window), so the set of compiled chunk programs is bounded by
    ``len(widths)`` for ANY prompt length.  The default single-``chunk``
    plan compiles one extra program per distinct final-partial width —
    fine for batch jobs, a compile explosion for serving admission
    (models/serving.py passes denominations).
    """
    from .llama import head_logits

    W = cfg.sliding_window
    if W is None:
        raise ValueError("prefill_rolling requires cfg.sliding_window")
    if attn_fn is not None:
        raise ValueError("prefill_rolling owns its attention; attn_fn must be None")
    B, P = prompt.shape
    cos, sin = cfg_rope_tables(cfg, P)
    cache = init_rolling_cache(cfg, B)

    # Host-side chunk plan.
    plan = []
    c0 = 0
    if widths is None:
        C = min(chunk or W, W, P)
        while c0 < P:
            plan.append(min(C, P - c0))
            c0 += plan[-1]
    else:
        for width in widths:
            width = min(int(width), W)
            while P - c0 >= width:
                plan.append(width)
                c0 += width
        if c0 != P:
            raise ValueError(
                f"widths={tuple(widths)} cannot cover prompt length {P} "
                f"(include 1 as the smallest denomination)")

    # Jitted chunk step (module-level compile cache keyed on cfg; jit's own
    # cache keys one shape per distinct plan width).  Eager per-op dispatch
    # here costs O(P/C * n_layers) round trips — fatal on a tunneled device
    # at ~100 ms per dispatch.
    run_chunk = _compiled_prefill_chunk(cfg)

    h_last = None
    c0 = 0
    for Cc in plan:
        # Rope slices are cut on the host so the compiled signature sees
        # [Cc, ...] — independent of P (a full-table argument would
        # recompile the chunk program for every distinct prompt length).
        h_last, cache = run_chunk(params, cache, prompt[:, c0:c0 + Cc],
                                  jnp.asarray(c0, jnp.int32),
                                  cos[c0:c0 + Cc], sin[c0:c0 + Cc])
        c0 += Cc
    logits = head_logits(h_last[:, -1:], params["final_norm"],
                         params["lm_head"], cfg.norm_eps)
    return logits[:, 0], cache


@functools.cache
def _compiled_prefill_chunk(cfg: LlamaConfig):
    """jit'd single-chunk body of :func:`prefill_rolling` for one config.

    ``c0`` (the chunk's global start) is traced, so every full-size chunk
    reuses ONE compiled program; only the final partial chunk (different
    width) triggers a second trace."""
    from ..ops.attention import (finalize_partial, merge_partials,
                                 partial_attention)
    from .llama import decoder_layer

    W = cfg.sliding_window
    n_rep = cfg.n_heads // cfg.n_kv_heads

    quant = cfg.kv_quant == "int8"

    def run_chunk(params, cache, tokens_c, c0, cos_c, sin_c):
        """One chunk through every layer; returns (h, new cache)."""
        Cc = tokens_c.shape[1]
        slots = (c0 + jnp.arange(Cc)) % W
        # Reorder the cache by absolute position: slot s holds the latest
        # p < c0 with p % W == s; gathering positions c0-W..c0-1 in order
        # lets partial_attention mask in plain global coordinates.
        order = (c0 - W + jnp.arange(W)) % W
        h = embed_tokens(params, tokens_c, cfg)  # [B, Cc, D]

        def chunk_attn(kc, vc, ksc, vsc):
            """attn_fn for decoder_layer: past (the rolling cache, in
            position order) + present (the chunk itself, causal) as two
            mergeable online-softmax partials.  int8 caches dequantize the
            gathered window up front — an O(window) transient per layer,
            matching the path's O(chunk + window) memory contract."""
            def attn(q, k, v):
                kco = jnp.take(kc, order, axis=2)
                vco = jnp.take(vc, order, axis=2)
                if quant:
                    from ..ops.quantize import dequantize_kv

                    kco = dequantize_kv(kco, jnp.take(ksc, order, axis=2),
                                        q.dtype)
                    vco = dequantize_kv(vco, jnp.take(vsc, order, axis=2),
                                        q.dtype)
                past = partial_attention(
                    q, repeat_kv(kco, n_rep), repeat_kv(vco, n_rep),
                    q_offset=c0, kv_offset=c0 - W, causal=True, window=W,
                    kv_min=0)
                here = partial_attention(
                    q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
                    q_offset=c0, kv_offset=c0, causal=True, window=W)
                return finalize_partial(*merge_partials(past, here),
                                        out_dtype=q.dtype)

            return attn

        # Python loop over layers (stacked tree sliced per layer): the one
        # decoder_layer body the scan forward uses, with a per-layer
        # cache-aware attn_fn; the returned post-RoPE grouped k/v feed the
        # circular slot write.
        new = {name: [] for name in cache}
        for li in range(cfg.n_layers):
            lp = jax.tree_util.tree_map(lambda a: a[li], params["layers"])
            kc, vc = cache["k"][li], cache["v"][li]
            ksc = cache["k_scale"][li] if quant else None
            vsc = cache["v_scale"][li] if quant else None
            h, _aux, k, v, _stats = decoder_layer(lp, h, cfg, cos_c, sin_c,
                                                  chunk_attn(kc, vc, ksc,
                                                             vsc))
            if quant:
                from ..ops.quantize import quantize_kv

                k, k_s = quantize_kv(k)
                v, v_s = quantize_kv(v)
                new["k_scale"].append(ksc.at[:, :, slots].set(k_s))
                new["v_scale"].append(vsc.at[:, :, slots].set(v_s))
            new["k"].append(kc.at[:, :, slots, :].set(k))
            new["v"].append(vc.at[:, :, slots, :].set(v))
        return h, {name: jnp.stack(v) for name, v in new.items()}

    # The caller rebinds its cache to the returned one each chunk, so the
    # input cache can be donated: the update happens in place instead of
    # holding two full O(window) caches live per dispatch.
    return jax.jit(run_chunk, donate_argnums=(1,))


def validate_prompt_lengths(prompt_lengths, B: int, P: int):
    """The ragged-batch lengths contract shared by every generation entry
    point (generate, generate_speculative, generate_lookup): concrete
    [B] int values in [1, P].  Under jit the downstream gathers would
    clamp and return wrong continuations silently, so tracers are
    rejected — ragged generation must be called outside jit (the entry
    points compile their own prefill+decode programs internally).
    Returns the [B] int32 lengths."""
    lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if lengths.shape != (B,):
        raise ValueError(f"prompt_lengths must be [{B}], got {lengths.shape}")
    if isinstance(lengths, jax.core.Tracer):
        raise ValueError(
            "ragged generation (prompt_lengths) must be called outside "
            "jit: length validation needs concrete values")
    if bool((lengths < 1).any()) or bool((lengths > P).any()):
        raise ValueError(
            f"prompt_lengths must be in [1, {P}]; got {lengths.tolist()}")
    return lengths


def _filter_logits(logits, temperature: float, top_k: Optional[int],
                   top_p: Optional[float]):
    """The sampling distribution's logits: temperature-scaled, then top-k /
    nucleus masked (NEG_BIG outside the kept set).  ``softmax`` of the
    result IS the distribution :func:`_sample` draws from — speculative
    decoding's acceptance rule needs exactly it (models/speculative.py).
    Only meaningful for ``temperature > 0``."""
    l = logits / temperature
    if top_k is not None and top_k < l.shape[-1]:
        kth = lax.top_k(l, top_k)[0][..., -1:]
        l = jnp.where(l < kth, NEG_BIG, l)
    if top_p is not None and top_p < 1.0:
        srt = jnp.sort(l, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p  # exclusive prefix mass; index 0 stays
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1, keepdims=True)
        l = jnp.where(l < thresh, NEG_BIG, l)
    return l


def _sample(logits, key, temperature: float, top_k: Optional[int],
            top_p: Optional[float]):
    """One sampled token id per row of ``logits [B, V]``.  Static Python
    ``temperature``/``top_k``/``top_p`` (baked into the compiled step):
    temperature 0 = greedy; top-k keeps the k largest logits; top-p keeps
    the smallest prefix of the sorted distribution with cumulative mass
    >= top_p (the first token is always kept)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l = _filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, l, axis=-1).astype(jnp.int32)


@functools.cache
def _compiled_generate(cfg: LlamaConfig, B: int, P: int, max_new: int,
                       max_len: int, temperature: float,
                       top_k: Optional[int], top_p: Optional[float],
                       ragged: bool = False, eos_id: Optional[int] = None,
                       want_logprobs: bool = False):
    """jit'd prefill + decode scan for one (shape, sampling) signature.

    The whole generation is ONE dispatch: flash prefill, then a
    ``lax.scan`` of sample->decode steps — no per-token host round trip
    (the XLA-friendly decode loop; on this sandbox's tunneled device a
    per-token dispatch costs ~100 ms against a ~30 µs decode step).

    ``ragged``: the compiled fn takes per-row prompt lengths; every row
    decodes from its own cursor (see :func:`generate`'s contract).

    Sliding-window configs on the aligned path decode through a ROLLING
    cache of ``sliding_window`` slots whenever that is smaller than
    ``max_len`` — cache memory is O(window) however long the generation
    runs, and the tokens are bit-identical to the full-cache path (pinned
    by tests/test_generate.py).
    """
    rope = cfg_rope_tables(cfg, max_len)
    W = cfg.sliding_window
    rolling = (not ragged) and W is not None and W < max_len

    def run(params, prompt, key, lengths):
        if rolling:
            if P <= W:
                # prefill's own padding already yields the rolling layout
                # (slot p % W == p while p < W).
                logits, cache = prefill(params, cfg, prompt, W)
            else:
                logits, cache = prefill(params, cfg, prompt, P)  # unpadded
                # Keep the last W positions, each at its slot p % W.  The T
                # axis sits at index 3 for every cache leaf (k/v AND the
                # int8 format's scale arrays, which only drop trailing D).
                src = (P - W) + ((jnp.arange(W) - (P - W)) % W)
                cache = jax.tree_util.tree_map(
                    lambda a: jnp.take(a, src, axis=3), cache)
            pos0 = jnp.asarray(P, jnp.int32)
        elif ragged:
            # Right-padded prompts: causal attention already confines every
            # real position to real prefixes (pad positions only corrupt
            # their OWN states, which are never read — hence the dense-only
            # restriction: MoE capacity is shared batch-wide), so the same
            # prefill fills the cache, gathering each row's next-token
            # logits from its own length-1 position.
            logits, cache = prefill(params, cfg, prompt, max_len,
                                    logit_positions=lengths - 1)
            pos0 = lengths
        else:
            logits, cache = prefill(params, cfg, prompt, max_len)
            pos0 = jnp.asarray(P, jnp.int32)

        done0 = jnp.zeros((B,), bool)

        def emit(logits, sub, done):
            """Sample one token per row (+, when asked, its UNFILTERED
            model logprob — the serving-API convention); rows already
            done emit eos at logprob 0 (the fill is mechanical, not a
            model event).  ``want_logprobs`` is in the compile key, so
            the default path keeps its logprob-free graph."""
            tok = _sample(logits, sub, temperature, top_k, top_p)
            if want_logprobs:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits, -1), tok[:, None], -1)[:, 0]
            else:
                lp = jnp.zeros((B,), jnp.float32)
            if eos_id is not None:
                tok = jnp.where(done, jnp.int32(eos_id), tok)
                lp = jnp.where(done, 0.0, lp)
                done = done | (tok == eos_id)
            return tok, lp, done

        def step(carry, _):
            cache, logits, key, pos, done = carry
            key, sub = jax.random.split(key)
            tok, lp, done = emit(logits, sub, done)
            logits, cache = decode_step(params, cache, tok, pos, cfg, rope,
                                        rolling=rolling)
            return (cache, logits, key, pos + 1, done), (tok, lp)

        # Scan max_new - 1 sample->decode pairs, then sample the final token
        # outside the scan: its decode_step would compute logits nothing
        # ever reads.
        init = (cache, logits, key, pos0, done0)
        (cache, logits, key, _, done), (toks, lps) = lax.scan(
            step, init, None, length=max_new - 1)
        key, sub = jax.random.split(key)
        last, last_lp, _ = emit(logits, sub, done)
        toks = jnp.concatenate([toks, last[None]], axis=0)
        lps = jnp.concatenate([lps, last_lp[None]], axis=0)
        return toks.T, lps.T  # [B, max_new] each

    return jax.jit(run)


def generate(params: dict, cfg: LlamaConfig, prompt, max_new_tokens: int,
             *, temperature: float = 0.0, key: Optional[jax.Array] = None,
             max_len: Optional[int] = None, top_k: Optional[int] = None,
             top_p: Optional[float] = None, prompt_lengths=None,
             eos_id: Optional[int] = None, return_logprobs: bool = False):
    """Autoregressive generation.  prompt: [B, P] int32.

    Aligned batch (default): returns ``[B, P + max_new_tokens]`` (prompt +
    continuation).  temperature=0 -> greedy; otherwise softmax sampling
    with ``key``, optionally truncated by ``top_k`` and/or nucleus
    ``top_p``.  ``eos_id``: rows that emit it keep emitting it for the
    rest of the scan (the conventional eos-fill; the compiled step count
    stays static).

    Ragged batch: pass ``prompt_lengths`` ([B] ints, RIGHT-padded prompt)
    and every row decodes from its own length — one compiled scan serves
    mixed prompt sizes.  Returns only the NEW tokens ``[B,
    max_new_tokens]`` (row b's continuation of ``prompt[b, :lengths[b]]``;
    the caller stitches ragged rows).

    ``return_logprobs``: additionally return ``[B, max_new_tokens]`` f32 —
    each emitted token's UNFILTERED model logprob (log-softmax of the raw
    logits at its position, the serving-API convention, regardless of
    temperature/top-k/top-p), with eos-fill positions at 0.0 (the fill is
    mechanical, not a model event).  Pinned against teacher-forced
    recomputation by tests/test_generate.py.
    """
    B, P = prompt.shape
    if max_new_tokens < 1:
        # The compiled scan has length max_new_tokens - 1; a zero/negative
        # count would die deep inside tracing after paying a full prefill.
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = P + max_new_tokens
    if max_len is None:
        max_len = total
    elif max_len < total:
        # Without this, dynamic_update_slice clamps every position >= max_len
        # onto the last cache slot and generation silently corrupts.
        raise ValueError(
            f"max_len={max_len} is smaller than prompt + max_new_tokens={total}"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    # LongRoPE: pin the factor regime to this run's horizon ONCE —
    # prefill and decode tables are built at different lengths and must
    # agree (llama.resolve_longrope).
    from .llama import resolve_longrope

    cfg = resolve_longrope(cfg, max_len)
    ragged = prompt_lengths is not None
    if ragged:
        from .moe import require_dropless

        # Pad tokens share the batch-wide expert capacity; only provable
        # droplessness keeps real rows untouched (moe.py, the single
        # source of the rule).
        require_dropless(cfg, "ragged generation")
        lengths = validate_prompt_lengths(prompt_lengths, B, P)
    else:
        lengths = jnp.zeros((B,), jnp.int32)  # unused placeholder
    run = _compiled_generate(cfg, B, P, max_new_tokens, max_len,
                             float(temperature), top_k, top_p, ragged,
                             None if eos_id is None else int(eos_id),
                             want_logprobs=bool(return_logprobs))
    toks, lps = run(params, prompt, key, lengths)
    out = toks if ragged else jnp.concatenate([prompt, toks], axis=1)
    if return_logprobs:
        return out, lps
    return out
