"""Paged continuous batching: the serving cache as a shared page pool.

The dense :class:`~starway_tpu.models.serving.SlotServer` cache reserves
``n_slots x max_len`` positions whatever the requests actually use;
paging (the vLLM block-table idea, built TPU-first here) shares one pool
of fixed-size pages across slots, so HBM scales with LIVE tokens:

* pool ``k/v [L, n_pages, Hkv, page, D]`` — sized by expected total
  tokens in flight, independent of ``n_slots x max_len``;
* host-managed page tables ``[n_slots, max_pages]`` + free list; pages
  allocate lazily as each cursor grows and return to the pool the
  moment a request finishes or is cancelled;
* decode attention walks the table INSIDE the pallas kernel's DMA
  stream (ops/pallas_paged.py) — no dense view is ever materialised,
  and bandwidth per token equals the dense stream kernel's.

Page id 0 is a reserved TRASH page: freed slots' table rows point at it,
so the chunk program's frozen-cursor writes for dead slots (the dense
design's "overwritten before read" invariant does not survive page
REUSE) land in scratch that no live slot ever attends.

Greedy outputs are bit-identical to the dense SlotServer and the
standalone ``generate()`` oracle (tests/test_paged.py) — paging changes
WHERE bytes live, never what attention computes.  v1 scope: full-causal
bf16/f32 models (no sliding-window/rolling, no int8 pools, no prefix
sharing — each refused loudly).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas_paged import paged_decode_attention
from .generate import _sample, cached_layer_scan, prefill
from .llama import LlamaConfig, cfg_rope_tables, embed_tokens, matmul_w, rmsnorm
from .serving import SlotServer, _bucket, make_chunk_scan_step


def init_paged_pool(cfg: LlamaConfig, n_pages: int, page: int) -> dict:
    """k/v pools ``[L, n_pages, Hkv, page, D]`` (page 0 is the trash
    page)."""
    hd = cfg.head_dim
    shape = (cfg.n_layers, n_pages, cfg.n_kv_heads, page, hd)
    return {"k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype)}


def paged_decode_step(params, pool, table, token, pos, cfg: LlamaConfig,
                      rope):
    """One token in, next-token logits out, over the paged pool.

    Mirrors :func:`~starway_tpu.models.generate.decode_step` exactly —
    same :func:`cached_layer_scan` body — with page-table write/attend
    closures: the write scatters each slot's k/v into
    ``pool[table[b, pos_b // page], head, pos_b % page]``, and attention
    streams the slot's pages through the paged kernel.  token/pos: [B]
    (per-slot cursors, the serving shape)."""
    page = pool["k"].shape[3]
    cos, sin = rope
    pos = jnp.asarray(pos, jnp.int32)
    pids = jnp.take_along_axis(table, (pos // page)[:, None], axis=1)[:, 0]
    offs = pos % page
    cos_p = cos[pos][:, None, None, :]
    sin_p = sin[pos][:, None, None, :]

    def write(c, u):
        # c [n_pages, Hkv, page, D] (one layer's pool slice in the scan);
        # u [B, Hkv, 1, D].  Distinct slots own distinct pages (allocator
        # invariant), so the scatter indices never collide.
        return c.at[pids, :, offs, :].set(u[:, :, 0, :])

    def attend(q, lc):
        return paged_decode_attention(q, lc["k"], lc["v"], table, pos)

    h = embed_tokens(params, token, cfg)[:, None, :]
    h, out = cached_layer_scan(params, pool, h, cos_p, sin_p, cfg, write,
                               attend)
    h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = matmul_w(h[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, out


@functools.cache
def _compiled_paged_admit(cfg: LlamaConfig, p_bucket: int, page: int,
                          temperature: float, top_k: Optional[int],
                          top_p: Optional[float]):
    """Prefill one request and scatter its cache into ``p_bucket // page``
    pool pages; returns (pool, first token).  One compile per bucket."""
    npb = p_bucket // page

    def run(params, pool, prompt, length, pids, key):
        logits, small = prefill(params, cfg, prompt, p_bucket,
                                logit_positions=length[None] - 1)
        pool = dict(pool)
        for name in ("k", "v"):
            # small[name] [L, 1, Hkv, p_bucket, D] -> [L, npb, Hkv, page, D]
            L, _, hkv, _, d = small[name].shape
            paged = small[name].reshape(L, hkv, npb, page, d).transpose(
                0, 2, 1, 3, 4)
            pool[name] = pool[name].at[:, pids].set(paged)
        tok = _sample(logits, key, temperature, top_k, top_p)[0]
        return pool, tok

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_paged_chunk(cfg: LlamaConfig, max_len: int, chunk: int,
                          temperature: float, top_k: Optional[int],
                          top_p: Optional[float], eos_id: Optional[int]):
    """The chunk program over the pool: identical control flow to the
    dense ``_compiled_chunk`` (liveness, budgets, eos, emission mask) —
    only the decode step is paged."""
    rope = cfg_rope_tables(cfg, max_len)

    def run(params, pool, table, token, pos, live, remaining, key):
        step = make_chunk_scan_step(
            lambda pool, token, pos: paged_decode_step(
                params, pool, table, token, pos, cfg, rope),
            max_len, temperature, top_k, top_p, eos_id)
        (pool, token, pos, live, remaining, key), (toks, mask) = lax.scan(
            step, (pool, token, pos, live, remaining, key), None,
            length=chunk)
        return pool, token, pos, live, remaining, key, toks, mask

    return jax.jit(run, donate_argnums=(1,))


class PagedSlotServer(SlotServer):
    """Continuous batching over a shared page pool.

    >>> srv = PagedSlotServer(params, cfg, n_slots=8, max_len=512,
    ...                       page=64, n_pages=33)
    >>> rid = srv.submit(prompt, max_new_tokens=32)
    >>> done = srv.run()

    Same queue/streaming/cancel surface and the same greedy-equals-
    ``generate()`` guarantee as the dense server; the difference is
    memory: ``n_pages`` bounds TOTAL live tokens (``(n_pages - 1) *
    page``), not per-slot reservations, so short requests don't pay for
    ``max_len``, and pages recycle the moment a request finishes.
    A request whose prompt the pool cannot cover yet simply STAYS
    QUEUED (step() catches the allocator's RuntimeError and retries once
    in-flight work frees pages); lazy per-chunk growth exhausting the
    pool mid-generation raises RuntimeError — preemption is not wired,
    so size ``n_pages`` for the expected concurrency.
    """

    def __init__(self, params, cfg: LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, page: int = 64,
                 n_pages: Optional[int] = None, chunk: int = 8,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 on_tokens=None):
        if cfg.sliding_window is not None:
            raise NotImplementedError(
                "paged serving v1 is full-causal; sliding-window models "
                "already serve in O(window) via the rolling SlotServer")
        if cfg.kv_quant != "none":
            raise NotImplementedError(
                "int8 paged pools are not wired yet; use the dense "
                "SlotServer for kv_quant='int8'")
        if max_len % page:
            raise ValueError(f"page ({page}) must divide max_len "
                             f"({max_len})")
        self.page = int(page)
        self.max_pages = max_len // page
        if n_pages is None:
            n_pages = 1 + n_slots * self.max_pages  # dense-equivalent
        if n_pages < 2:
            raise ValueError("need n_pages >= 2 (page 0 is the trash page)")
        self.n_pages = int(n_pages)
        # Buckets must be page multiples so admission scatters whole pages.
        b, buckets = page, []
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
        super().__init__(params, cfg, n_slots=n_slots, max_len=max_len,
                         chunk=chunk, temperature=temperature, top_k=top_k,
                         top_p=top_p, eos_id=eos_id,
                         prompt_buckets=tuple(sorted(set(buckets))),
                         seed=seed, on_tokens=on_tokens)

    # ------------------------------------------------------------- hooks
    def _make_cache(self):
        return init_paged_pool(self.cfg, self.n_pages, self.page)

    def _post_init(self) -> None:
        # Host-side allocator: every slot starts on the trash page.
        self._tables = np.zeros((self.n_slots, self.max_pages), np.int32)
        self._free = list(range(1, self.n_pages))

    def _on_slot_freed(self, slot: int) -> None:
        for pid in self._tables[slot]:
            if pid != 0:
                self._free.append(int(pid))
        self._tables[slot] = 0

    @property
    def pages_in_use(self) -> int:
        """Live pool pages (the memory the paging saves elsewhere)."""
        return self.n_pages - 1 - len(self._free)

    def _alloc_to(self, slot: int, n_needed: int) -> None:
        row = self._tables[slot]
        have = int((row != 0).sum())
        if n_needed > self.max_pages:
            n_needed = self.max_pages
        if n_needed > have and len(self._free) < n_needed - have:
            raise RuntimeError(
                f"page pool exhausted: slot {slot} needs "
                f"{n_needed - have} more page(s), {len(self._free)} free "
                f"(n_pages={self.n_pages}); finish/cancel requests or "
                f"size the pool for the workload")
        for i in range(have, n_needed):
            row[i] = self._free.pop()

    # --------------------------------------------------------- admission
    def register_prefix(self, tokens) -> int:
        raise NotImplementedError(
            "prefix caching over the page pool (shared read-only pages) "
            "is not wired yet; use the dense SlotServer for prefixes")

    def _admit(self, slot: int, rid: int, prompt: np.ndarray,
               max_new: int, prefix=None) -> None:
        assert prefix is None  # submit() rejects prefixes (no registry)
        self.key, sub = jax.random.split(self.key)
        pb = _bucket(len(prompt), self.buckets)
        self._alloc_to(slot, pb // self.page)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :len(prompt)] = prompt
        pids = jnp.asarray(self._tables[slot, :pb // self.page])
        admit = _compiled_paged_admit(self.cfg, pb, self.page,
                                      *self.sampling)
        self.cache, tok = admit(self.params, self.cache,
                                jnp.asarray(padded),
                                jnp.asarray(len(prompt), jnp.int32),
                                pids, sub)
        self._finish_admit(slot, rid, tok, len(prompt), max_new)

    # ------------------------------------------------------------ decode
    def _run_chunk(self, sub):
        # Lazy growth: every live slot needs pages covering its cursor's
        # reach this chunk (writes go through table[pos // page]).
        live = np.asarray(self.live)
        pos = np.asarray(self.pos)
        for slot in range(self.n_slots):
            if live[slot]:
                # The chunk writes positions pos .. pos+chunk-1 (reads
                # only written positions), so the last page touched is
                # (pos+chunk-1) // page.
                reach = min(int(pos[slot]) + self.chunk, self.max_len)
                self._alloc_to(slot, -(-reach // self.page))
        run = _compiled_paged_chunk(self.cfg, self.max_len, self.chunk,
                                    *self.sampling, self.eos_id)
        (self.cache, self.token, self.pos, self.live, self.remaining,
         _key, toks, mask) = run(self.params, self.cache,
                                 jnp.asarray(self._tables), self.token,
                                 self.pos, self.live, self.remaining, sub)
        return toks, mask
