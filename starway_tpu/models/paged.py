"""Paged continuous batching: the serving cache as a shared page pool.

The dense :class:`~starway_tpu.models.serving.SlotServer` cache reserves
``n_slots x max_len`` positions whatever the requests actually use;
paging (the vLLM block-table idea, built TPU-first here) shares one pool
of fixed-size pages across slots, so HBM scales with LIVE tokens:

* pool ``k/v [L, n_pages, Hkv, page, D]`` — sized by expected total
  tokens in flight, independent of ``n_slots x max_len``;
* host-managed page tables ``[n_slots, max_pages]`` + free list; pages
  allocate lazily as each cursor grows and return to the pool the
  moment a request finishes or is cancelled;
* decode attention walks the table INSIDE the pallas kernel's DMA
  stream (ops/pallas_paged.py) — no dense view is ever materialised,
  and bandwidth per token equals the dense stream kernel's.

Page id 0 is a reserved TRASH page: freed slots' table rows point at it,
so the chunk program's frozen-cursor writes for dead slots (the dense
design's "overwritten before read" invariant does not survive page
REUSE) land in scratch that no live slot ever attends.

PREFIX SHARING, zero copy: a registered prefix's whole pages are
REFERENCED by every suffix request (refcounted; only the partial tail
page the suffix continues inside is copied per slot) — strictly less
admission work and less memory than the dense server's per-slot row
copy, and the natural payoff of the paged layout.

Greedy outputs are bit-identical to the dense SlotServer and the
standalone ``generate()`` oracle (tests/test_paged.py) — paging changes
WHERE bytes live, never what attention computes.  v1 scope: full-causal
bf16/f32 models (sliding-window/rolling and int8 pools are refused
loudly).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas_paged import paged_decode_attention
from .generate import _sample, cached_layer_scan, prefill
from .llama import LlamaConfig, cfg_rope_tables, embed_tokens, matmul_w, rmsnorm
from .serving import SlotServer, _bucket, make_chunk_scan_step


def init_paged_pool(cfg: LlamaConfig, n_pages: int, page: int) -> dict:
    """k/v pools ``[L, n_pages, Hkv, page, D]`` (page 0 is the trash
    page)."""
    hd = cfg.head_dim
    shape = (cfg.n_layers, n_pages, cfg.n_kv_heads, page, hd)
    return {"k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype)}


def paged_decode_step(params, pool, table, token, pos, cfg: LlamaConfig,
                      rope):
    """One token in, next-token logits out, over the paged pool.

    Mirrors :func:`~starway_tpu.models.generate.decode_step` exactly —
    same :func:`cached_layer_scan` body — with page-table write/attend
    closures: the write scatters each slot's k/v into
    ``pool[table[b, pos_b // page], head, pos_b % page]``, and attention
    streams the slot's pages through the paged kernel.  token/pos: [B]
    (per-slot cursors, the serving shape)."""
    page = pool["k"].shape[3]
    cos, sin = rope
    pos = jnp.asarray(pos, jnp.int32)
    pids = jnp.take_along_axis(table, (pos // page)[:, None], axis=1)[:, 0]
    offs = pos % page
    cos_p = cos[pos][:, None, None, :]
    sin_p = sin[pos][:, None, None, :]

    def write(c, u):
        # c [n_pages, Hkv, page, D] (one layer's pool slice in the scan);
        # u [B, Hkv, 1, D].  Distinct slots own distinct pages (allocator
        # invariant), so the scatter indices never collide.
        return c.at[pids, :, offs, :].set(u[:, :, 0, :])

    def attend(q, lc):
        return paged_decode_attention(q, lc["k"], lc["v"], table, pos)

    h = embed_tokens(params, token, cfg)[:, None, :]
    h, out = cached_layer_scan(params, pool, h, cos_p, sin_p, cfg, write,
                               attend)
    h = rmsnorm(h, params["final_norm"], cfg.norm_eps)
    logits = matmul_w(h[:, 0, :], params["lm_head"]).astype(jnp.float32)
    return logits, out


@functools.cache
def _compiled_paged_admit(cfg: LlamaConfig, p_bucket: int, page: int,
                          temperature: float, top_k: Optional[int],
                          top_p: Optional[float]):
    """Prefill one request and scatter its cache into ``p_bucket // page``
    pool pages; returns (pool, first token).  One compile per bucket."""
    npb = p_bucket // page

    def run(params, pool, prompt, length, pids, key):
        logits, small = prefill(params, cfg, prompt, p_bucket,
                                logit_positions=length[None] - 1)
        pool = dict(pool)
        for name in ("k", "v"):
            # small[name] [L, 1, Hkv, p_bucket, D] -> [L, npb, Hkv, page, D]
            L, _, hkv, _, d = small[name].shape
            paged = small[name].reshape(L, hkv, npb, page, d).transpose(
                0, 2, 1, 3, 4)
            pool[name] = pool[name].at[:, pids].set(paged)
        tok = _sample(logits, key, temperature, top_k, top_p)[0]
        return pool, tok

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_paged_chunk(cfg: LlamaConfig, max_len: int, chunk: int,
                          temperature: float, top_k: Optional[int],
                          top_p: Optional[float], eos_id: Optional[int]):
    """The chunk program over the pool: identical control flow to the
    dense ``_compiled_chunk`` (liveness, budgets, eos, emission mask) —
    only the decode step is paged."""
    rope = cfg_rope_tables(cfg, max_len)

    def run(params, pool, table, token, pos, live, remaining, key):
        step = make_chunk_scan_step(
            lambda pool, token, pos: paged_decode_step(
                params, pool, table, token, pos, cfg, rope),
            max_len, temperature, top_k, top_p, eos_id)
        (pool, token, pos, live, remaining, key), (toks, mask) = lax.scan(
            step, (pool, token, pos, live, remaining, key), None,
            length=chunk)
        return pool, token, pos, live, remaining, key, toks, mask

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_paged_prefix_write(cfg: LlamaConfig, p_bucket: int, page: int,
                                 n_full: int):
    """Prefill a PREFIX once; scatter its ``n_full`` whole pages into the
    pool and return the remainder as one padded tail page (junk above
    ``plen % page`` — overwritten by the suffix ingest before any read).
    One compile per (prefix bucket, n_full)."""

    def run(params, pool, prompt, length, pids):
        _logits, small = prefill(params, cfg, prompt, p_bucket,
                                 logit_positions=length[None] - 1)
        tails = {}
        pool = dict(pool)
        for name in ("k", "v"):
            L, _, hkv, _, d = small[name].shape
            paged = small[name].reshape(L, hkv, p_bucket // page, page,
                                        d).transpose(0, 2, 1, 3, 4)
            if n_full:
                pool[name] = pool[name].at[:, pids].set(paged[:, :n_full])
            # The page holding positions [n_full*page, plen): the suffix
            # continues inside it, so it is copied per slot, not shared.
            tails[name] = (paged[:, n_full] if n_full < p_bucket // page
                           else jnp.zeros((L, hkv, page, d),
                                          small[name].dtype))
        return pool, tails["k"], tails["v"]

    return jax.jit(run, donate_argnums=(1,))


@functools.cache
def _compiled_paged_prefix_admit(cfg: LlamaConfig, s_bucket: int, page: int,
                                 max_pages: int, has_tail: bool,
                                 temperature: float, top_k: Optional[int],
                                 top_p: Optional[float]):
    """Admit (shared prefix, fresh suffix): copy the prefix's partial
    tail page into the slot's first OWN page, then ingest the suffix as
    ONE C=s_bucket chunk forward over the page table (write-then-attend,
    the decode-path semantics) and sample from the suffix's last real
    position.  One compile per (suffix bucket, has_tail) — plen is a
    traced argument, so prefixes of any length share the program."""
    rope = cfg_rope_tables(cfg, max_pages * page)
    cos, sin = rope

    def run(params, pool, tail_k, tail_v, row, suffix, s_len, plen, key):
        pool = dict(pool)
        if has_tail:
            own0 = row[0, plen // page]
            pool["k"] = pool["k"].at[:, own0].set(tail_k)
            pool["v"] = pool["v"].at[:, own0].set(tail_v)

        spos = plen + jnp.arange(s_bucket)      # suffix positions
        pids_c = row[0, spos // page]
        offs = spos % page
        cos_p = cos[spos][None, None, :, :]
        sin_p = sin[spos][None, None, :, :]

        def write(c, u):
            # u [1, Hkv, s_bucket, D] -> scatter rows at (pid, :, off).
            return c.at[pids_c, :, offs, :].set(u[0].transpose(1, 0, 2))

        def attend(q, lc):
            return paged_decode_attention(q, lc["k"], lc["v"], row,
                                          plen[None])

        from .llama import embed_tokens, head_logits

        h = embed_tokens(params, suffix[0], cfg)[None]  # [1, s_bucket, D]
        h, pool = cached_layer_scan(params, pool, h, cos_p, sin_p, cfg,
                                    write, attend)
        logits = head_logits(h[:, s_len - 1][:, None], params["final_norm"],
                             params["lm_head"],
                             cfg.norm_eps)[:, 0]
        tok = _sample(logits, key, temperature, top_k, top_p)[0]
        return pool, tok

    if not has_tail:
        # No tail operands at all: page-aligned prefixes must not pay two
        # dead [L, Hkv, page, D] transfers per admission.
        def run_no_tail(params, pool, row, suffix, s_len, plen, key):
            return run(params, pool, None, None, row, suffix, s_len, plen,
                       key)

        return jax.jit(run_no_tail, donate_argnums=(1,))
    return jax.jit(run, donate_argnums=(1,))


class PagedSlotServer(SlotServer):
    """Continuous batching over a shared page pool.

    >>> srv = PagedSlotServer(params, cfg, n_slots=8, max_len=512,
    ...                       page=64, n_pages=33)
    >>> rid = srv.submit(prompt, max_new_tokens=32)
    >>> done = srv.run()

    Same queue/streaming/cancel surface and the same greedy-equals-
    ``generate()`` guarantee as the dense server; the difference is
    memory: ``n_pages`` bounds TOTAL live tokens (``(n_pages - 1) *
    page``), not per-slot reservations, so short requests don't pay for
    ``max_len``, and pages recycle the moment a request finishes.
    A request whose prompt the pool cannot cover yet simply STAYS
    QUEUED (step() catches the allocator's RuntimeError and retries once
    in-flight work frees pages); lazy per-chunk growth exhausting the
    pool mid-generation raises RuntimeError — preemption is not wired,
    so size ``n_pages`` for the expected concurrency.
    """

    def __init__(self, params, cfg: LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, page: int = 64,
                 n_pages: Optional[int] = None, chunk: int = 8,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 on_tokens=None):
        if cfg.sliding_window is not None:
            raise NotImplementedError(
                "paged serving v1 is full-causal; sliding-window models "
                "already serve in O(window) via the rolling SlotServer")
        if cfg.kv_quant != "none":
            raise NotImplementedError(
                "int8 paged pools are not wired yet; use the dense "
                "SlotServer for kv_quant='int8'")
        if max_len % page:
            raise ValueError(f"page ({page}) must divide max_len "
                             f"({max_len})")
        self.page = int(page)
        self.max_pages = max_len // page
        if n_pages is None:
            n_pages = 1 + n_slots * self.max_pages  # dense-equivalent
        if n_pages < 2:
            raise ValueError("need n_pages >= 2 (page 0 is the trash page)")
        self.n_pages = int(n_pages)
        # Buckets must be page multiples so admission scatters whole pages.
        b, buckets = page, []
        while b < max_len:
            buckets.append(b)
            b *= 2
        buckets.append(max_len)
        super().__init__(params, cfg, n_slots=n_slots, max_len=max_len,
                         chunk=chunk, temperature=temperature, top_k=top_k,
                         top_p=top_p, eos_id=eos_id,
                         prompt_buckets=tuple(sorted(set(buckets))),
                         seed=seed, on_tokens=on_tokens)

    # ------------------------------------------------------------- hooks
    def _make_cache(self):
        return init_paged_pool(self.cfg, self.n_pages, self.page)

    def _post_init(self) -> None:
        # Host-side allocator: every slot starts on the trash page.
        self._tables = np.zeros((self.n_slots, self.max_pages), np.int32)
        self._free = list(range(1, self.n_pages))
        # Shared prefix pages: refcount = registry (1) + referencing
        # slots; a page returns to the pool at refcount 0.
        self._page_refs: dict[int, int] = {}

    def _on_slot_freed(self, slot: int) -> None:
        for pid in self._tables[slot]:
            pid = int(pid)
            if pid == 0:
                continue
            if pid in self._page_refs:  # shared prefix page
                self._page_refs[pid] -= 1
                if self._page_refs[pid] == 0:  # prefix already dropped
                    del self._page_refs[pid]
                    self._free.append(pid)
            else:
                self._free.append(pid)
        self._tables[slot] = 0

    @property
    def pages_in_use(self) -> int:
        """Live pool pages (the memory the paging saves elsewhere)."""
        return self.n_pages - 1 - len(self._free)

    def _alloc_to(self, slot: int, n_needed: int) -> None:
        """Extend the slot's table to ``n_needed`` pages (the prefix of
        the row is whatever admission set — shared or owned)."""
        row = self._tables[slot]
        have = int((row != 0).sum())
        if n_needed > self.max_pages:
            n_needed = self.max_pages
        if n_needed > have and len(self._free) < n_needed - have:
            raise RuntimeError(
                f"page pool exhausted: slot {slot} needs "
                f"{n_needed - have} more page(s), {len(self._free)} free "
                f"(n_pages={self.n_pages}); finish/cancel requests or "
                f"size the pool for the workload")
        for i in range(have, n_needed):
            row[i] = self._free.pop()

    # --------------------------------------------------------- admission
    def register_prefix(self, tokens) -> int:
        """Prefill a shared prefix ONCE into pool pages; requests with
        ``prefix=pid`` then REFERENCE its whole pages (zero copy, pages
        refcounted across slots) and copy only the partial tail page the
        suffix continues inside — strictly less admission work AND less
        memory than the dense server's per-slot row copy."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if len(tokens) < 1:
            raise ValueError("empty prefix")
        if len(tokens) + self.buckets[0] + 1 > self.max_len:
            raise ValueError(
                f"prefix ({len(tokens)}) + smallest suffix bucket "
                f"({self.buckets[0]}) + 1 exceeds max_len={self.max_len}")
        plen = len(tokens)
        n_full = plen // self.page
        if len(self._free) < n_full:
            raise RuntimeError(
                f"page pool exhausted: prefix needs {n_full} page(s), "
                f"{len(self._free)} free")
        pids = [self._free.pop() for _ in range(n_full)]
        pb = _bucket(max(plen, self.page), self.buckets)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :plen] = tokens
        reg = _compiled_paged_prefix_write(self.cfg, pb, self.page, n_full)
        self.cache, tail_k, tail_v = reg(
            self.params, self.cache, jnp.asarray(padded),
            jnp.asarray(plen, jnp.int32), jnp.asarray(pids, jnp.int32))
        for pid_page in pids:
            self._page_refs[pid_page] = 1  # the registry's own reference
        pid = self._next_pid
        self._next_pid += 1
        has_tail = plen % self.page != 0
        self._prefixes[pid] = (
            (tuple(pids), tail_k if has_tail else None,
             tail_v if has_tail else None), plen)
        return pid

    def drop_prefix(self, pid: int) -> None:
        """Release the registry's reference; whole pages return to the
        pool once no admitted slot still reads them."""
        if any(p == pid for _rid, _pr, _mn, p in self._pending):
            raise ValueError(
                f"prefix {pid} is still referenced by queued requests; "
                f"run()/step() them first")
        (pids, _tk, _tv), _plen = self._prefixes.pop(pid)
        for pid_page in pids:
            self._page_refs[pid_page] -= 1
            if self._page_refs[pid_page] == 0:
                del self._page_refs[pid_page]
                self._free.append(pid_page)

    def _admit(self, slot: int, rid: int, prompt: np.ndarray,
               max_new: int, prefix=None) -> None:
        if prefix is not None:
            return self._admit_prefixed(slot, rid, prompt, max_new, prefix)
        self.key, sub = jax.random.split(self.key)
        pb = _bucket(len(prompt), self.buckets)
        self._alloc_to(slot, pb // self.page)
        padded = np.zeros((1, pb), np.int32)
        padded[0, :len(prompt)] = prompt
        pids = jnp.asarray(self._tables[slot, :pb // self.page])
        admit = _compiled_paged_admit(self.cfg, pb, self.page,
                                      *self.sampling)
        self.cache, tok = admit(self.params, self.cache,
                                jnp.asarray(padded),
                                jnp.asarray(len(prompt), jnp.int32),
                                pids, sub)
        self._finish_admit(slot, rid, tok, len(prompt), max_new)

    def _admit_prefixed(self, slot: int, rid: int, suffix: np.ndarray,
                        max_new: int, prefix: int) -> None:
        if prefix not in self._prefixes:
            raise KeyError(f"prefix {prefix} was dropped while request "
                           f"{rid} waited in the queue")
        (shared, tail_k, tail_v), plen = self._prefixes[prefix]
        n_full = plen // self.page
        sb = _bucket(len(suffix), self.buckets)
        need = -(-(plen + sb) // self.page)
        n_own = need - n_full
        if len(self._free) < n_own:
            raise RuntimeError(
                f"page pool exhausted: prefixed admission needs {n_own} "
                f"own page(s), {len(self._free)} free")
        row = self._tables[slot]
        row[:n_full] = shared
        for i in range(n_full, need):
            row[i] = self._free.pop()
        for pid_page in shared:
            self._page_refs[pid_page] += 1
        self.key, sub = jax.random.split(self.key)
        padded = np.zeros((1, sb), np.int32)
        padded[0, :len(suffix)] = suffix
        has_tail = tail_k is not None
        admit = _compiled_paged_prefix_admit(
            self.cfg, sb, self.page, self.max_pages, has_tail,
            *self.sampling)
        args = (jnp.asarray(self._tables[slot:slot + 1]),
                jnp.asarray(padded), jnp.asarray(len(suffix), jnp.int32),
                jnp.asarray(plen, jnp.int32), sub)
        if has_tail:
            self.cache, tok = admit(self.params, self.cache, tail_k,
                                    tail_v, *args)
        else:
            self.cache, tok = admit(self.params, self.cache, *args)
        self._finish_admit(slot, rid, tok, plen + len(suffix), max_new)

    # ------------------------------------------------------------ decode
    def _run_chunk(self, sub):
        # Lazy growth: every live slot needs pages covering its cursor's
        # reach this chunk (writes go through table[pos // page]).
        live = np.asarray(self.live)
        pos = np.asarray(self.pos)
        for slot in range(self.n_slots):
            if live[slot]:
                # The chunk writes positions pos .. pos+chunk-1 (reads
                # only written positions), so the last page touched is
                # (pos+chunk-1) // page.
                reach = min(int(pos[slot]) + self.chunk, self.max_len)
                self._alloc_to(slot, -(-reach // self.page))
        run = _compiled_paged_chunk(self.cfg, self.max_len, self.chunk,
                                    *self.sampling, self.eos_id)
        (self.cache, self.token, self.pos, self.live, self.remaining,
         _key, toks, mask) = run(self.params, self.cache,
                                 jnp.asarray(self._tables), self.token,
                                 self.pos, self.live, self.remaining, sub)
        return toks, mask
