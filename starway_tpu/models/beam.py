"""Beam-search decoding: single-dispatch, static shapes, XLA-first.

Completes the decoding-strategy surface next to greedy/sampled
``generate()`` and the speculative decoders: K beams per row advance
through ONE compiled ``lax.scan`` (no per-token host round trip), with
the cache laid out as ``[L, B*K, ...]`` batch rows so every existing
decode machinery piece (``decode_step``'s per-row cursors, the pallas
grouped-stream kernel, int8 caches, W8 weights via ``matmul_w``) applies
unchanged.

Beam reordering is the one beam-specific cost: after each step's
top-K-of-(K·V) selection, surviving beams gather their parents' cache
rows — a cache-sized HBM shuffle per step.  That is the standard price of
exact beam search; latency-sensitive serving wants ``generate`` or the
speculative paths instead (DESIGN.md §9), and the docstring says so.

No reference counterpart (/root/reference is a transport library); this is
the TPU build's serving-stack extension implementing standard beam search.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .generate import NEG_BIG, decode_step, prefill
from .llama import LlamaConfig, cfg_rope_tables


@functools.cache
def _compiled_beam(cfg: LlamaConfig, B: int, K: int, P: int, max_new: int,
                   max_len: int, eos_id: Optional[int]):
    rope = cfg_rope_tables(cfg, max_len)

    def run(params, prompt):
        logits, cache = prefill(params, cfg, prompt, max_len)  # rows = B
        logp0 = jax.nn.log_softmax(logits, -1)  # [B, V]
        V = logp0.shape[-1]

        # Seed K beams per row from the top-K first tokens (distinct by
        # construction), and tile the prompt cache K ways: beam k of row
        # b lives at batch row b*K + k from here on.
        top0, tok0 = lax.top_k(logp0, K)  # [B, K]
        scores = top0
        cache = jax.tree_util.tree_map(
            lambda a: jnp.repeat(a, K, axis=1), cache)
        toks0 = tok0.reshape(B * K)
        fin0 = (jnp.zeros((B, K), bool) if eos_id is None
                else tok0 == eos_id)

        out0 = jnp.zeros((B, K, max_new), jnp.int32)
        out0 = out0.at[:, :, 0].set(tok0)

        def step(carry, i):
            cache, scores, toks, fin, out = carry
            logits, cache = decode_step(params, cache, toks, P + i, cfg,
                                        rope)
            logp = jax.nn.log_softmax(logits, -1).reshape(B, K, V)
            if eos_id is not None:
                # A finished beam continues ONLY as itself: force its
                # candidate set to {eos} at zero added logprob, so it
                # competes with live expansions at its frozen score.
                frozen = jnp.full((B, K, V), NEG_BIG).at[:, :, eos_id].set(0.0)
                logp = jnp.where(fin[:, :, None], frozen, logp)
            cand = scores[:, :, None] + logp  # [B, K, V]
            scores, flat = lax.top_k(cand.reshape(B, K * V), K)
            parent = flat // V  # [B, K]
            tok = (flat % V).astype(jnp.int32)

            # Reorder per-beam state to the surviving parents.
            take = functools.partial(jnp.take_along_axis, axis=1)
            out = take(out, parent[:, :, None])
            fin = take(fin, parent)
            if eos_id is not None:
                fin = fin | (tok == eos_id)
            out = out.at[:, :, i + 1].set(tok)
            # Cache rows follow their parents: [L, B, K, ...] gather on
            # the beam axis — the per-step HBM shuffle beam search pays.
            idx = (jnp.arange(B)[:, None] * K + parent).reshape(B * K)
            cache = jax.tree_util.tree_map(
                lambda a: jnp.take(a, idx, axis=1), cache)
            return (cache, scores, tok.reshape(B * K), fin, out), None

        init = (cache, scores, toks0, fin0, out0)
        (cache, scores, _, fin, out), _ = lax.scan(
            step, init, jnp.arange(max_new - 1))
        # Beams come out of top_k score-sorted already.
        return out, scores, fin

    return jax.jit(run)


def generate_beam(params: dict, cfg: LlamaConfig, prompt,
                  max_new_tokens: int, *, beams: int = 4,
                  eos_id: Optional[int] = None, max_len: Optional[int] = None,
                  return_all: bool = False):
    """Beam-search generation.  prompt: [B, P] int32; K = ``beams``.

    Returns ``[B, P + max_new_tokens]`` — each row's highest-scoring beam
    (sum of token logprobs; beams that emit ``eos_id`` freeze their score
    and eos-fill, competing at that frozen score thereafter).  With
    ``return_all=True`` returns ``(sequences [B, K, max_new], scores
    [B, K], finished [B, K])`` score-sorted per row.  Audit property
    (pinned by tests/test_beam.py): every score is exactly the
    teacher-forced sum of the beam's emitted tokens' logprobs UP TO AND
    INCLUDING its first ``eos_id`` — the sampled eos counts, the forced
    eos-fill tail after it contributes nothing (a finished beam's score
    is frozen, which is what lets it compete fairly with live beams).

    ``beams=1`` reduces to greedy ``generate()`` bit-exactly.  Aligned
    batches, full caches (no sliding-window rolling), dense or MoE —
    but note each scan step re-gathers the K-way cache, so MoE capacity
    interactions and the per-step HBM shuffle make this a
    quality-search tool, not the latency path.
    """
    B, P = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if beams < 1:
        raise ValueError(f"beams must be >= 1, got {beams}")
    if beams > cfg.vocab_size:
        raise ValueError(f"beams={beams} exceeds the vocab ({cfg.vocab_size})")
    if cfg.sliding_window is not None:
        raise ValueError("beam search needs full caches; rolling-cache "
                         "support is not wired")
    total = P + max_new_tokens
    if max_len is None:
        max_len = total
    elif max_len < total:
        raise ValueError(
            f"max_len={max_len} is smaller than prompt + max_new_tokens="
            f"{total}")
    from .llama import resolve_longrope

    cfg = resolve_longrope(cfg, max_len)  # one factor regime per run
    run = _compiled_beam(cfg, B, int(beams), P, max_new_tokens, max_len,
                         None if eos_id is None else int(eos_id))
    out, scores, fin = run(params, prompt)
    # No post-hoc eos-fill needed: a finished beam's only candidate
    # continuation inside the scan IS eos, so every surviving tail after
    # a first eos is already eos (pinned by tests/test_beam.py).
    if return_all:
        return out, scores, fin
    best = out[:, 0]  # top_k sorts scores descending
    return jnp.concatenate([prompt, best], axis=1)
