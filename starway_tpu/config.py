"""Environment-driven configuration.

The reference is configured purely through environment variables and CLI flags
(reference: SURVEY.md section 5 "Config / flag system"; src/starway/__init__.py:14,
benchmark.md:114-126 for ``UCX_TLS``).  The TPU build mirrors that shape:

``STARWAY_TLS``
    Comma-separated transport preference list, analogous to ``UCX_TLS``.
    Known transports: ``inproc`` (same-process fast path, what ICI device
    transfers ride on), ``sm`` (same-host shared-memory rings negotiated
    over the TCP handshake, see core/shmring.py -- the analogue of UCX's
    posix/sysv shm transport), ``tcp`` (cross-process / DCN bootstrap
    path), ``ici`` / ``dcn`` (device-plane selectors used by the device
    layer).  Default: all enabled.

``STARWAY_SM_RING``
    Per-direction shared-memory ring size in bytes (rounded up to a power
    of two; default 1 MiB -- sized to stay cache-resident, see
    core/shmring.py).

``STARWAY_HOST``
    Routable host address advertised in worker-address blobs (default
    ``127.0.0.1``).

``STARWAY_RNDV_THRESHOLD``
    Payload size in bytes above which sends switch from eager (local
    completion = fully handed to the transport) to rendezvous-style streaming
    (local completion = transmission begun; delivery requires ``aflush``).
    Mirrors UCX eager/RNDV split (reference: src/bindings/main.cpp:954-980).

``STARWAY_NATIVE``
    "1" (default) = use the C++ engine extension when built, "0" = force the
    pure-Python engine.

``STARWAY_BACKEND``
    Device-plane backend: ``auto`` (default), ``tpu``, or ``cpu``.

``STARWAY_DEVPULL``
    "1" (default) = negotiate the PJRT transfer-server pull path for device
    payloads crossing processes (device-to-device, no host staging --
    see device.py TransferManager); "0" = always stage via the framed
    stream.

``STARWAY_DEVPULL_MIN``
    Minimum device payload size in bytes to use the pull path (default
    65536); smaller payloads ride the framed stream, where one small copy
    beats a pull round-trip.

``STARWAY_DECODE_STREAM``
    "1" (default) = the decode-attention kernel's streaming variant
    (double-buffered manual DMA, ops/pallas_decode.py); "0" = the
    grid-pipelined variant — the escape hatch if the manual-DMA lowering
    misbehaves on a backend it has not been measured on.

``STARWAY_SM_FORCE_ATOMICS``
    "1" = route the Python sm ring's cursor ops through the native lib's
    acquire/release atomics even on x86 (the off-x86 code path, made
    testable on x86 CI; see core/shmring.py).

``STARWAY_CHUNK``
    Data-plane pipelining granularity in bytes (default 256 KiB; 0
    disables pipelining).  Device payloads crossing the framed stream are
    staged device-to-host one chunk at a time so the D2H of chunk k+1
    overlaps the transport write of chunk k, and receive-side host-to-
    device placement of completed chunks overlaps the remaining stream
    reads (DESIGN.md §12).  Also sizes the reusable host staging-buffer
    pool that replaces per-transfer allocation.

``STARWAY_CONNECT_TIMEOUT``
    Per-attempt connect + handshake deadline in seconds (default 3.0).
    Both engines honour it; ``aconnect(..., timeout=)`` overrides it per
    call on the Python engine.  Mirrors UCX's ``UCX_..._TIMEOUT`` knobs
    replacing what used to be a hard-coded constant in core/engine.py.

``STARWAY_KEEPALIVE``
    Peer-liveness keepalive interval in seconds (default 0 = disabled,
    matching the reference contract "peer death leaves posted recvs
    pending").  When > 0 and both peers negotiated ``"ka": "ok"`` in the
    handshake, each engine PINGs idle peers every interval and declares a
    peer dead after ``STARWAY_KEEPALIVE_MISSES`` silent intervals: the
    conn is torn down, its in-flight matcher state purged, and pending
    receives fail with the stable ``"not connected"`` keyword.  The
    analogue of UCX's ``UCX_KEEPALIVE_INTERVAL`` / err-handling mode.

``STARWAY_KEEPALIVE_MISSES``
    Silent keepalive intervals tolerated before a peer is declared dead
    (default 3).

``STARWAY_SESSION``
    "1" = negotiate the resilient-session layer (off by default for seed
    parity).  Session-enabled Client<->Server pairs survive connection
    death mid-transfer: HELLO carries a stable session id + epoch, every
    eager DATA/ctl frame is sequence-numbered (frames.py T_SEQ), receivers
    ACK cumulatively (T_ACK) and drop duplicate seqs, senders keep a
    bounded replay journal of unacked frames, and on conn death the client
    transparently redials (exponential backoff) and both sides replay from
    the peer's cumulative ACK -- in-flight asend/arecv/aflush complete
    late instead of failing.  Only session expiry
    (``STARWAY_SESSION_GRACE`` exceeded, or the peer answers the resume
    handshake with a new epoch) fails them, with the stable
    ``"session expired"`` reason.  See DESIGN.md §14.

``STARWAY_SESSION_JOURNAL_BYTES``
    Replay-journal cap per connection direction in bytes (default 16 MiB).
    When unacknowledged journaled frames reach the cap, further sends
    *block* (they park unframed and drain as ACKs free space) instead of
    growing the journal without bound.

``STARWAY_SESSION_GRACE``
    Seconds a dead session-enabled connection may stay resumable (default
    30).  Past the grace window the session expires: suspended ops fail
    with ``"session expired"`` and the seed failure contract applies from
    then on.

``STARWAY_RAILS``
    Number of parallel transport lanes ("rails") a client opens to each
    server (default 1).  With N > 1 the primary HELLO offers
    ``"rails": "<N>"``; a striping-capable acceptor confirms
    ``"rails": "ok"`` and the connector dials N-1 extra TCP conns, each
    attached to the primary endpoint via the ``"rail_of"`` handshake key
    (no new server endpoint is created).  Rails are the stripe targets of
    the multi-rail data plane (DESIGN.md §17); an old peer simply never
    confirms and the extra dials are skipped -- all pairings interoperate.
    On a same-host sm-upgraded primary the extra rails stay on TCP, so
    one message can ride sm and tcp concurrently.

``STARWAY_STRIPE_THRESHOLD``
    Payload size in bytes at or above which a send on a railed connection
    is striped: split at ``STARWAY_STRIPE_CHUNK`` granularity, the chunks
    dispatched across every live rail with completion-driven work
    stealing, and reassembled by offset at the receiver (wire frame
    T_SDATA, core/frames.py).  Default 0 = striping off (seed parity:
    every send rides exactly one lane).  Striped sends use rendezvous
    local-completion semantics regardless of size and the payload is
    pinned by reference until the receiver's T_SACK -- delivery, as
    always, is promised only by ``aflush``.

``STARWAY_STRIPE_CHUNK``
    Stripe granularity in bytes (default: 4x the ``STARWAY_CHUNK`` §12
    staging granularity = 1 MiB, the measured sweet spot on the 1-core
    dev box -- smaller chunks pay a sendmsg per chunk, larger ones
    starve the work stealing; floor 4 KiB).  Each chunk is an
    independent self-describing frame (msg id, offset, total), which is
    what makes chunk-level work stealing, rail-death redistribution, and
    receiver-side offset dedup possible.

``STARWAY_STRIPE_WEIGHTED``
    "1" = lane-weighted tail claiming (default off).  The stripe
    scheduler always tracks a per-lane EWMA of delivered throughput
    (bytes of each completed chunk over its claim-to-written wall time);
    with the knob armed, a lane whose EWMA has fallen below half the
    fastest live lane's *declines to steal one of the last chunks* of a
    message (the tail, where a slow lane's final chunk IS the message's
    completion time), leaving it for a faster lane's next refill.
    Dispatch-time claims are never declined, so a chunk can never
    strand: the fastest live lane never declines, and every requeue path
    re-feeds all lanes unconditionally.  Both engines implement the
    identical policy.  See DESIGN.md §17.

``STARWAY_FC_WINDOW``
    Receiver-driven flow-control window in bytes (default 0 = off, seed
    parity).  When > 0 the handshake offers ``"fc": "<bytes>"`` and, once
    both peers confirm, each direction's eager traffic is governed by the
    RECEIVER's advertised window: the sender debits it per eager DATA
    payload, parks sends unframed-FIFO when it runs dry (block, never
    OOM; one oversized frame is admitted against an idle window so a
    single payload above the window cannot deadlock), and the receiver
    returns T_CREDIT grants as unexpected messages are matched or
    drained.  Sends above ``STARWAY_RNDV_THRESHOLD`` switch to the
    receiver-pulled RTS/CTS path and never consume window.  A parked
    send with a ``timeout=`` deadline is shed locally with the stable
    ``"timed out"`` reason (overload degrades to op timeouts, not conn
    or process death).  See DESIGN.md §18.

``STARWAY_UNEXP_BYTES``
    Per-connection ceiling on unexpected-queue payload bytes (default
    0 = unbounded, seed parity).  A last-resort overload breaker for
    peers that never negotiated ``fc``: a connection whose own
    un-granted spill crosses the cap is reset instead of letting the
    process OOM (total residency is bounded by cap x live conns, and
    the offender -- never an innocent peer -- takes the reset).  With
    ``fc`` negotiated the credit window keeps well-behaved peers under
    the cap.

``STARWAY_INTEGRITY``
    "1" = negotiate the end-to-end data-integrity plane (off by default
    for seed parity: no ``"csum"`` handshake key, no T_CSUM/T_SNACK
    frames, byte-stream sm rings).  Once both peers confirm ``csum``,
    every framed message is preceded by a T_CSUM frame carrying a CRC32C
    over the frame's header+payload (plus a header-only CRC so routing
    fields are validated before the payload streams into user buffers),
    and sm ring writes become per-slot records with a seqno+checksum
    trailer so torn/partial writes are detected at dequeue.  Verification
    failures are *recoverable*: a corrupt striped T_SDATA chunk is NACKed
    (T_SNACK) and only that chunk retransmits; a corrupt non-striped
    frame poisons the conn with the stable ``"corrupt"`` reason -- which
    without sessions takes the §10 failure contract and with
    ``STARWAY_SESSION=1`` suspends + replays so ops still complete
    exactly-once with verified bytes.  See DESIGN.md §19.

``STARWAY_TRACE``
    "1" = record per-op lifecycle events (posted/matched/completed/
    failed, stage spans, connection churn) into a bounded per-worker ring
    in BOTH engines (core/swtrace.py, native sw_trace).  Default off:
    the hot path then carries a single ``is None`` check per op -- no
    allocation, no syscall.  Export with ``python -m starway_tpu.trace``
    or ``python -m starway_tpu.bench --trace PATH`` (Chrome/Perfetto).

``STARWAY_PROTO_TRACE``
    "1" = additionally record the swrefine protocol-event channel
    (DESIGN.md §22) into the same ring, in BOTH engines: one ``EV_PROTO``
    event per dispatched inbound frame (``rx:<FRAME>``), per ctl-plane
    frame handed to a transport (``tx:<FRAME>``), plus the conn lifecycle
    (``st:hello-sent``/``st:estab`` at creation, ``lost``/``resume``/
    ``expire``/``down``).  ``python -m starway_tpu.analysis refine
    --replay <ring dump>`` replays the channel through the protocol
    monitor automaton compiled from the engines' own state machines.
    Default off; setting it arms the trace ring even without
    STARWAY_TRACE.  The seed path (env unset) emits zero protocol events
    -- one ``is None`` check per frame, pinned by test.

``STARWAY_MONITOR``
    "1" = runtime conformance checking (swrefine, DESIGN.md §22): implies
    STARWAY_PROTO_TRACE, and every traced worker's protocol events are
    replayed through the monitor automaton in-process at worker
    retirement (plus on demand via ``core.monitor.check_all()`` -- the
    chaos soaks call it every run).  A violation records the divergence,
    dumps the §13 flight recorder, and fails the soak hard
    (``monitor.assert_clean()``).  Default off.

``STARWAY_TRACE_RING``
    Trace ring capacity in events per worker (default 4096; min 16).

``STARWAY_FLIGHT_DIR``
    Directory for flight-recorder dumps.  When set, the first op failure
    with a non-cancel reason, an engine emergency close, and a close
    after a fault each dump the worker's last-N trace events + counter
    snapshot as JSON there (post-mortem forensics, DESIGN.md §13).
    Setting it implicitly arms the trace ring even without STARWAY_TRACE.

``STARWAY_METRICS_INTERVAL``
    swscope live-telemetry sampling period in seconds (default 0 =
    sampler off, DESIGN.md §15).  When > 0, a daemon thread snapshots
    every worker's counter registry plus the per-conn gauges (TX queue
    depth/bytes, in-flight sends/recvs, session journal residency,
    staging-pool occupancy -- core/telemetry.py GAUGE_NAMES; native side
    via the ``sw_gauges`` ABI call) into a bounded ring of timestamped
    samples, surfaced through ``evaluate_perf_detail()["telemetry"]``
    and flight-recorder dumps.  The off path adds no per-op work: the
    sampler is a background thread, armed per worker at construction.

``STARWAY_METRICS_PATH``
    JSONL file the sampler appends each sample to (one JSON object per
    line).  Setting it arms the sampler even without
    STARWAY_METRICS_INTERVAL (at the 1 s default period).  View live or
    post-hoc with ``python -m starway_tpu.metrics <path>``.

``STARWAY_METRICS_ADDR``
    ``host:port`` for the sampler's live feed listener: each connecting
    viewer (``python -m starway_tpu.metrics host:port``) receives the
    JSONL sample stream as it is produced.  Also arms the sampler.

``STARWAY_METRICS_RING``
    In-memory telemetry sample ring capacity (default 512; min 16).
"""

from __future__ import annotations

import os

__all__ = [
    "transports_enabled",
    "advertised_host",
    "rndv_threshold",
    "chunk_bytes",
    "use_native",
    "device_backend",
    "devpull_enabled",
    "devpull_threshold",
    "decode_stream_enabled",
    "connect_timeout",
    "keepalive_interval",
    "keepalive_misses",
    "session_enabled",
    "session_journal_bytes",
    "session_grace",
    "stripe_rails",
    "stripe_threshold",
    "stripe_chunk",
    "stripe_weighted",
    "fc_window",
    "unexp_cap",
    "integrity_enabled",
    "trace_enabled",
    "proto_trace_enabled",
    "monitor_enabled",
    "trace_ring_size",
    "flight_dir",
    "metrics_interval",
    "metrics_path",
    "metrics_addr",
    "metrics_ring_size",
    "stall_ms",
]


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def transports_enabled() -> list[str]:
    raw = _env("STARWAY_TLS", "inproc,sm,tcp,ici,dcn")
    return [t.strip() for t in raw.split(",") if t.strip()]


def inproc_enabled() -> bool:
    return "inproc" in transports_enabled()


def sm_enabled() -> bool:
    # The pure-Python ring relies on x86-TSO store ordering for its
    # data-before-tail publication (core/shmring.py); ARM permits
    # store-store reordering and Python cannot fence.  Off x86 the ring
    # routes every cursor access through the native lib's acquire/release
    # atomics instead (shmring._use_portable_atomics) -- sm is only
    # refused when that lib is unavailable too.  CPython is still required
    # either way: the ring's data copies go through memoryview slices
    # whose program-order guarantees a JIT (PyPy, future CPython tiers)
    # may not preserve.  (The C++ engine uses real atomics throughout and
    # carries sm on any architecture/runtime.)
    import platform

    if platform.python_implementation() != "CPython":
        return False
    if "sm" not in transports_enabled():
        return False
    if platform.machine() not in ("x86_64", "AMD64"):
        from .core import native

        # build=False: this probe sits on the connection-setup path; a
        # missing lib means "no sm this process", never a g++ build.
        return native.atomics(build=False) is not None
    return True


def advertised_host() -> str:
    return _env("STARWAY_HOST", "127.0.0.1")


def devpull_enabled() -> bool:
    return _env("STARWAY_DEVPULL", "1") != "0"


def decode_stream_enabled() -> bool:
    return _env("STARWAY_DECODE_STREAM", "1") != "0"


def devpull_threshold() -> int:
    return int(_env("STARWAY_DEVPULL_MIN", str(64 * 1024)))


def rndv_threshold() -> int:
    return int(_env("STARWAY_RNDV_THRESHOLD", str(8 * 1024 * 1024)))


def chunk_bytes() -> int:
    """Data-plane pipelining granularity (STARWAY_CHUNK); 0 disables
    chunked staging and the receive-side placement overlap."""
    try:
        v = int(_env("STARWAY_CHUNK", str(256 * 1024)))
    except ValueError:
        return 256 * 1024
    return v if v > 0 else 0


def connect_timeout() -> float:
    try:
        v = float(_env("STARWAY_CONNECT_TIMEOUT", "3.0"))
    except ValueError:
        return 3.0
    return v if v > 0 else 3.0


def keepalive_interval() -> float:
    """Seconds between liveness PINGs; 0 (the default) disables detection
    entirely -- reference parity: peer death leaves posted recvs pending."""
    try:
        v = float(_env("STARWAY_KEEPALIVE", "0"))
    except ValueError:
        return 0.0
    return v if v > 0 else 0.0


def keepalive_misses() -> int:
    try:
        v = int(_env("STARWAY_KEEPALIVE_MISSES", "3"))
    except ValueError:
        return 3
    return v if v > 0 else 3


def session_enabled() -> bool:
    """Resilient-session layer (STARWAY_SESSION); off by default --
    seed parity: a dropped conn cancels every in-flight op."""
    return _env("STARWAY_SESSION", "0") not in ("", "0")


def session_journal_bytes() -> int:
    """Replay-journal cap per conn direction (STARWAY_SESSION_JOURNAL_BYTES);
    sends block (park unframed) when unacked journaled bytes reach it."""
    try:
        v = int(_env("STARWAY_SESSION_JOURNAL_BYTES", str(16 * 1024 * 1024)))
    except ValueError:
        return 16 * 1024 * 1024
    return max(4096, v)


def session_grace() -> float:
    """Seconds a dead session conn stays resumable (STARWAY_SESSION_GRACE);
    past it the session expires and ops fail with "session expired"."""
    try:
        v = float(_env("STARWAY_SESSION_GRACE", "30"))
    except ValueError:
        return 30.0
    return v if v > 0 else 30.0


def stripe_rails() -> int:
    """Parallel transport lanes per client connection (STARWAY_RAILS);
    1 (the default) keeps the single-conn seed topology."""
    try:
        v = int(_env("STARWAY_RAILS", "1"))
    except ValueError:
        return 1
    return max(1, min(16, v))


def stripe_threshold() -> int:
    """Payload bytes at/above which railed sends stripe
    (STARWAY_STRIPE_THRESHOLD); 0 (the default) disables striping."""
    try:
        v = int(_env("STARWAY_STRIPE_THRESHOLD", "0"))
    except ValueError:
        return 0
    return v if v > 0 else 0


def stripe_chunk() -> int:
    """Stripe granularity in bytes (STARWAY_STRIPE_CHUNK; defaults to 4x
    the §12 STARWAY_CHUNK staging granularity = 1 MiB)."""
    raw = _env("STARWAY_STRIPE_CHUNK", "")
    if raw:
        try:
            return max(4096, int(raw))
        except ValueError:
            pass
    return max(4096, 4 * (chunk_bytes() or 256 * 1024))


def stripe_weighted() -> bool:
    """Lane-weighted tail claiming (STARWAY_STRIPE_WEIGHTED); off by
    default -- pure work stealing, the PR-8 behaviour."""
    return _env("STARWAY_STRIPE_WEIGHTED", "0") not in ("", "0")


def fc_window() -> int:
    """Receiver credit window in bytes (STARWAY_FC_WINDOW); 0 (the
    default) disables flow control entirely -- seed parity: no "fc"
    handshake key, no T_CREDIT/T_RTS/T_CTS frames."""
    try:
        v = int(_env("STARWAY_FC_WINDOW", "0"))
    except ValueError:
        return 0
    return v if v > 0 else 0


def unexp_cap() -> int:
    """Hard unexpected-queue byte ceiling (STARWAY_UNEXP_BYTES); 0 (the
    default) keeps the seed's unbounded queue."""
    try:
        v = int(_env("STARWAY_UNEXP_BYTES", "0"))
    except ValueError:
        return 0
    return v if v > 0 else 0


def integrity_enabled() -> bool:
    """End-to-end integrity plane (STARWAY_INTEGRITY); off by default --
    seed parity: no "csum" handshake key, no checksum frames on the wire."""
    return _env("STARWAY_INTEGRITY", "0") not in ("", "0")


def trace_enabled() -> bool:
    """Per-op lifecycle tracing (STARWAY_TRACE); off by default -- the
    tracing-off hot path must stay allocation-free (DESIGN.md §13)."""
    return _env("STARWAY_TRACE", "0") not in ("", "0")


def proto_trace_enabled() -> bool:
    """swrefine protocol-event channel (STARWAY_PROTO_TRACE; implied by
    STARWAY_MONITOR); off by default -- the seed path emits no protocol
    events and pays one ``is None`` check per frame (DESIGN.md §22)."""
    return (_env("STARWAY_PROTO_TRACE", "0") not in ("", "0")
            or monitor_enabled())


def monitor_enabled() -> bool:
    """In-process protocol-monitor checking (STARWAY_MONITOR); off by
    default.  Implies the protocol-event channel (DESIGN.md §22)."""
    return _env("STARWAY_MONITOR", "0") not in ("", "0")


def trace_ring_size() -> int:
    """Trace ring capacity in events per worker (STARWAY_TRACE_RING)."""
    try:
        v = int(_env("STARWAY_TRACE_RING", "4096"))
    except ValueError:
        return 4096
    return max(16, v)


def flight_dir() -> str:
    """Flight-recorder output directory (STARWAY_FLIGHT_DIR); empty =
    recorder disabled."""
    return _env("STARWAY_FLIGHT_DIR", "")


def metrics_interval() -> float:
    """swscope sampler period in seconds (STARWAY_METRICS_INTERVAL);
    0 (the default) disables the sampler thread.  A metrics path/addr
    with no explicit interval samples at 1 s."""
    try:
        v = float(_env("STARWAY_METRICS_INTERVAL", "0"))
    except ValueError:
        return 0.0
    return v if v > 0 else 0.0


def metrics_path() -> str:
    """JSONL telemetry emitter path (STARWAY_METRICS_PATH); empty = off."""
    return _env("STARWAY_METRICS_PATH", "")


def metrics_addr() -> str:
    """host:port for the live telemetry feed (STARWAY_METRICS_ADDR);
    empty = no listener."""
    return _env("STARWAY_METRICS_ADDR", "")


def metrics_ring_size() -> int:
    """In-memory telemetry sample ring capacity (STARWAY_METRICS_RING)."""
    try:
        v = int(_env("STARWAY_METRICS_RING", "512"))
    except ValueError:
        return 512
    return max(16, v)


def stall_ms() -> float:
    """swpulse stall-sentinel threshold in milliseconds (STARWAY_STALL_MS);
    0 (the default) disables the sentinel entirely -- the seed path takes
    zero sentinel branches (DESIGN.md §25)."""
    try:
        v = float(_env("STARWAY_STALL_MS", "0"))
    except ValueError:
        return 0.0
    return v if v > 0 else 0.0


def use_native() -> bool:
    return _env("STARWAY_NATIVE", "1") == "1"


def device_backend() -> str:
    return _env("STARWAY_BACKEND", "auto")
